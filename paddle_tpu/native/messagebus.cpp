// Message bus: length-prefixed frames over TCP between named peers.
//
// TPU-native equivalent of the reference's brpc-based message bus
// (paddle/fluid/distributed/fleet_executor/message_bus.cc and the brpc
// channel underneath paddle/fluid/distributed/rpc/rpc_agent.cc) — the one
// transport shared by the fleet executor (interceptor messages), the RPC
// layer and the parameter-server client/server.  Payloads are opaque bytes
// (Python pickles on top); the bus only moves frames:
//
//     [int64 src_id][int64 payload_len][payload bytes]
//
// Design: one listener thread accepts connections; each inbound connection
// gets a reader thread that pushes complete frames onto a single
// mutex+condvar receive queue (mb_recv pops with a timeout).  Outbound
// connections are created lazily per peer on first send, with a bounded
// connect-retry window so a peer that comes up late (normal under cluster
// schedulers) does not fail the first send.  All functions are thread-safe.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Frame {
  int64_t src;
  std::vector<uint8_t> data;
};

struct Peer {
  std::string host;
  int port = 0;
  int fd = -1;
  std::mutex send_mu;
};

struct Bus {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> readers;
  std::vector<int> reader_fds;
  std::mutex readers_mu;

  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<Frame> queue;

  std::mutex peers_mu;
  std::map<int64_t, Peer*> peers;
  int connect_timeout_ms = 30000;

  ~Bus() {
    for (auto& kv : peers) delete kv.second;
  }
};

bool read_exact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void reader_loop(Bus* bus, int fd) {
  for (;;) {
    int64_t hdr[2];
    if (!read_exact(fd, hdr, sizeof(hdr))) break;
    int64_t len = hdr[1];
    if (len < 0 || len > (int64_t{1} << 40)) break;  // corrupt frame
    Frame f;
    f.src = hdr[0];
    f.data.resize(static_cast<size_t>(len));
    if (len > 0 && !read_exact(fd, f.data.data(), f.data.size())) break;
    {
      std::lock_guard<std::mutex> lk(bus->q_mu);
      bus->queue.push_back(std::move(f));
    }
    bus->q_cv.notify_one();
  }
  // deregister BEFORE closing so mb_stop never shutdown()s a recycled fd
  {
    std::lock_guard<std::mutex> lk(bus->readers_mu);
    auto& v = bus->reader_fds;
    v.erase(std::remove(v.begin(), v.end(), fd), v.end());
  }
  ::close(fd);
}

void accept_loop(Bus* bus) {
  for (;;) {
    sockaddr_in addr{};
    socklen_t alen = sizeof(addr);
    int fd = ::accept(bus->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    if (fd < 0) {
      if (bus->stop.load()) return;
      if (errno == EINTR) continue;
      return;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(bus->readers_mu);
    if (bus->stop.load()) {
      ::close(fd);
      return;
    }
    bus->reader_fds.push_back(fd);
    bus->readers.emplace_back(reader_loop, bus, fd);
  }
}

int connect_to(const std::atomic<bool>& stop, const std::string& host,
               int port, int timeout_ms) {
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  for (;;) {
    if (stop.load()) return -1;  // bus stopping: abandon the retry window
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    int fd = -1;
    if (::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) == 0) {
      fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
        ::close(fd);
        fd = -1;
      }
      ::freeaddrinfo(res);
    }
    if (fd >= 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    if (std::chrono::steady_clock::now() >= deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  }
}

}  // namespace

extern "C" {

void* mb_create(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr =
      (host && *host) ? ::inet_addr(host) : htonl(INADDR_ANY);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  Bus* bus = new Bus();
  bus->listen_fd = fd;
  bus->port = ntohs(addr.sin_port);
  bus->accept_thread = std::thread(accept_loop, bus);
  return bus;
}

int mb_port(void* h) { return static_cast<Bus*>(h)->port; }

void mb_set_connect_timeout(void* h, int timeout_ms) {
  static_cast<Bus*>(h)->connect_timeout_ms = timeout_ms;
}

int mb_add_peer(void* h, long long peer_id, const char* host, int port) {
  Bus* bus = static_cast<Bus*>(h);
  std::lock_guard<std::mutex> lk(bus->peers_mu);
  Peer*& p = bus->peers[peer_id];
  if (p == nullptr) p = new Peer();
  // send_mu keeps us from closing the fd under a concurrent mb_send
  // mid-write (same peers_mu -> send_mu order as mb_stop: no deadlock)
  std::lock_guard<std::mutex> slk(p->send_mu);
  if (p->host != host || p->port != port) {
    if (p->fd >= 0) {  // peer moved (elastic restart): drop the stale conn
      ::close(p->fd);
      p->fd = -1;
    }
    p->host = host;
    p->port = port;
  }
  return 0;
}

// 0 on success, -1 unknown peer, -2 connect/send failure.
int mb_send(void* h, long long my_id, long long peer_id, const void* data,
            long long len) {
  Bus* bus = static_cast<Bus*>(h);
  Peer* p = nullptr;
  {
    std::lock_guard<std::mutex> lk(bus->peers_mu);
    auto it = bus->peers.find(peer_id);
    if (it == bus->peers.end()) return -1;
    p = it->second;
  }
  std::lock_guard<std::mutex> lk(p->send_mu);
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (p->fd < 0) {
      p->fd = connect_to(bus->stop, p->host, p->port,
                         bus->connect_timeout_ms);
      if (p->fd < 0) return -2;
    }
    int64_t hdr[2] = {my_id, len};
    if (write_exact(p->fd, hdr, sizeof(hdr)) &&
        (len == 0 || write_exact(p->fd, data, static_cast<size_t>(len)))) {
      return 0;
    }
    ::close(p->fd);  // stale half-open conn (peer restarted): reconnect once
    p->fd = -1;
  }
  return -2;
}

// Returns payload length (>=0) with *src / *data set (caller must mb_free
// *data), -1 on timeout, -2 after shutdown.
long long mb_recv(void* h, long long* src, void** data, int timeout_ms) {
  Bus* bus = static_cast<Bus*>(h);
  std::unique_lock<std::mutex> lk(bus->q_mu);
  bus->q_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                     [&] { return !bus->queue.empty() || bus->stop.load(); });
  if (bus->queue.empty()) return bus->stop.load() ? -2 : -1;
  Frame f = std::move(bus->queue.front());
  bus->queue.pop_front();
  lk.unlock();
  *src = f.src;
  void* buf = ::malloc(f.data.size() ? f.data.size() : 1);
  if (!f.data.empty()) std::memcpy(buf, f.data.data(), f.data.size());
  *data = buf;
  return static_cast<long long>(f.data.size());
}

void mb_free(void* p) { ::free(p); }

// Two-phase teardown: mb_stop wakes every blocked mb_recv (they return -2)
// and joins all threads; mb_destroy frees the bus once the caller knows no
// thread can still be inside an mb_* call on this handle.
void mb_stop(void* h) {
  Bus* bus = static_cast<Bus*>(h);
  bus->stop.store(true);
  ::shutdown(bus->listen_fd, SHUT_RDWR);
  ::close(bus->listen_fd);
  if (bus->accept_thread.joinable()) bus->accept_thread.join();
  {
    std::lock_guard<std::mutex> lk(bus->peers_mu);
    for (auto& kv : bus->peers) {
      std::lock_guard<std::mutex> slk(kv.second->send_mu);
      if (kv.second->fd >= 0) {
        ::shutdown(kv.second->fd, SHUT_RDWR);
        ::close(kv.second->fd);
        kv.second->fd = -1;
      }
    }
  }
  std::vector<std::thread> readers;
  {
    // shutdown under the lock; join OUTSIDE it so an exiting reader can
    // deregister its fd (it takes readers_mu) without deadlocking us
    std::lock_guard<std::mutex> lk(bus->readers_mu);
    for (int fd : bus->reader_fds) ::shutdown(fd, SHUT_RDWR);
    readers.swap(bus->readers);
  }
  for (auto& t : readers)
    if (t.joinable()) t.join();
  bus->q_cv.notify_all();
}

void mb_destroy(void* h) { delete static_cast<Bus*>(h); }

}  // extern "C"
