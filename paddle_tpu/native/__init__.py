"""Native (C++) runtime components, loaded via ctypes.

The compute path is JAX/XLA/Pallas; these are the AROUND-the-compiler pieces
the reference implements in C++ (data feed, IO) — see native/dataio.cpp.
Libraries build on first use with the in-image toolchain and cache next to
the sources; every user has a pure-Python fallback, so a missing compiler
degrades gracefully.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS = {}


def load(name: str):
    """Load (building if needed) lib<name>.so from this directory; returns
    the ctypes CDLL or None when no toolchain is available."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        lib = os.path.join(_DIR, f"lib{name}.so")
        if (not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)):
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
                   src, "-o", lib + ".tmp"]
            try:
                subprocess.run(cmd, check=True, capture_output=True)
                os.replace(lib + ".tmp", lib)
            except (OSError, subprocess.CalledProcessError):
                _LIBS[name] = None
                return None
        try:
            _LIBS[name] = ctypes.CDLL(lib)
        except OSError:
            _LIBS[name] = None
        return _LIBS[name]
