// Native data-IO core: memory-mapped token-file reader + shuffled batcher.
//
// Reference analog: the reference's C++ DataFeed/Dataset machinery
// (paddle/fluid/framework/data_feed.cc, data_set.cc) that feeds trainers
// without Python in the loop.  TPU-native scope: pretraining token streams —
// fixed-width int32/uint16 rows in a flat binary file, mmap'd (zero-copy,
// page-cache backed), gathered into contiguous batches by worker threads
// with a seeded Fisher-Yates epoch shuffle.  Exposed via a C ABI for ctypes
// (no pybind11 in this image).
//
// Build: cc -O3 -shared -fPIC dataio.cpp -o libdataio.so  (see dataio.py)

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct TokenFile {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t bytes = 0;
  int64_t row_len = 0;     // tokens per row
  int64_t n_rows = 0;
  int itemsize = 4;        // 4 = int32, 2 = uint16
};

struct Sampler {
  std::vector<int64_t> order;
  std::atomic<int64_t> cursor{0};
  uint64_t seed = 0;
  int64_t epoch = -1;
};

}  // namespace

extern "C" {

// Open a flat token file; returns handle ptr or null.  row_len in tokens.
void* dataio_open(const char* path, int64_t row_len, int itemsize) {
  if (row_len <= 0 || (itemsize != 2 && itemsize != 4)) return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    ::close(fd);
    return nullptr;
  }
  void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (m == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(m, st.st_size, MADV_SEQUENTIAL);
  auto* tf = new TokenFile;
  tf->fd = fd;
  tf->base = static_cast<const uint8_t*>(m);
  tf->bytes = static_cast<size_t>(st.st_size);
  tf->row_len = row_len;
  tf->itemsize = itemsize;
  tf->n_rows = st.st_size / (row_len * itemsize);
  return tf;
}

int64_t dataio_num_rows(void* h) {
  return h ? static_cast<TokenFile*>(h)->n_rows : -1;
}

// Copy `count` rows given explicit indices into out (int32, row-major).
// Returns rows copied, or -1 on a bad index.
int64_t dataio_gather(void* h, const int64_t* indices, int64_t count,
                      int32_t* out) {
  auto* tf = static_cast<TokenFile*>(h);
  if (!tf) return -1;
  const int64_t L = tf->row_len;
  for (int64_t i = 0; i < count; ++i) {
    const int64_t r = indices[i];
    if (r < 0 || r >= tf->n_rows) return -1;
    const uint8_t* src = tf->base + static_cast<size_t>(r) * L * tf->itemsize;
    int32_t* dst = out + i * L;
    if (tf->itemsize == 4) {
      std::memcpy(dst, src, static_cast<size_t>(L) * 4);
    } else {
      const uint16_t* s16 = reinterpret_cast<const uint16_t*>(src);
      for (int64_t j = 0; j < L; ++j) dst[j] = static_cast<int32_t>(s16[j]);
    }
  }
  return count;
}

// Seeded epoch sampler: deterministic Fisher-Yates over row order.
void* dataio_sampler_new(void* h, uint64_t seed) {
  auto* tf = static_cast<TokenFile*>(h);
  if (!tf) return nullptr;
  auto* s = new Sampler;
  s->seed = seed;
  s->order.resize(static_cast<size_t>(tf->n_rows));
  return s;
}

void dataio_sampler_epoch(void* sp, int64_t epoch, int shuffle) {
  auto* s = static_cast<Sampler*>(sp);
  if (!s) return;
  const int64_t n = static_cast<int64_t>(s->order.size());
  for (int64_t i = 0; i < n; ++i) s->order[static_cast<size_t>(i)] = i;
  if (shuffle) {
    std::mt19937_64 rng(s->seed ^ (0x9e3779b97f4a7c15ULL * (epoch + 1)));
    for (int64_t i = n - 1; i > 0; --i) {
      std::uniform_int_distribution<int64_t> d(0, i);
      std::swap(s->order[static_cast<size_t>(i)],
                s->order[static_cast<size_t>(d(rng))]);
    }
  }
  s->epoch = epoch;
  s->cursor.store(0);
}

// Fill the next batch (thread-safe claim of a contiguous index range).
// Returns rows filled (< batch_size at epoch end; 0 when exhausted).
int64_t dataio_next_batch(void* h, void* sp, int64_t batch_size,
                          int32_t* out) {
  auto* tf = static_cast<TokenFile*>(h);
  auto* s = static_cast<Sampler*>(sp);
  if (!tf || !s) return -1;
  const int64_t n = static_cast<int64_t>(s->order.size());
  const int64_t start = s->cursor.fetch_add(batch_size);
  if (start >= n) return 0;
  const int64_t count = std::min(batch_size, n - start);
  return dataio_gather(tf, s->order.data() + start, count, out);
}

void dataio_sampler_free(void* sp) { delete static_cast<Sampler*>(sp); }

void dataio_close(void* h) {
  auto* tf = static_cast<TokenFile*>(h);
  if (!tf) return;
  munmap(const_cast<uint8_t*>(tf->base), tf->bytes);
  ::close(tf->fd);
  delete tf;
}

}  // extern "C"
