// Parameter-server tables: sharded sparse + dense embedding storage with
// per-slot SGD update rules, behind a C ABI for ctypes.
//
// TPU-native equivalent of the reference's C++ PS tables
// (paddle/fluid/distributed/ps/table/memory_sparse_table.cc,
// memory_dense_table.cc) and SGD rules (sparse_sgd_rule.cc: naive /
// adagrad / adam).  The Python PSServer hosts these tables and serves
// pull/push over the RPC layer; ids hash-shard across servers the way the
// reference's get_sparse_shard does (key % shard_num).
//
// Sparse rows initialize lazily on first pull (uniform in
// [-initial_range, initial_range], seeded per id so every server/restart
// agrees).  Internally the table is bucketed (SHARDS-way) with per-bucket
// mutexes so concurrent pulls/pushes from the RPC worker pool scale.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kShards = 32;

enum class Rule { kNaive, kAdagrad, kAdam };

struct Opt {
  Rule rule = Rule::kNaive;
  float lr = 0.01f;
  float initial_range = 0.0f;
  float initial_g2sum = 0.0f;  // adagrad epsilon seed
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f;
};

// per-row payload: [w(dim)] [slots...]  (adagrad: g2sum(dim); adam:
// m(dim) v(dim) beta1_pow beta2_pow)
int slot_floats(Rule r, int dim) {
  switch (r) {
    case Rule::kNaive:
      return 0;
    case Rule::kAdagrad:
      return dim;
    case Rule::kAdam:
      return 2 * dim + 2;
  }
  return 0;
}

// splitmix64: deterministic per-id init so every shard/restart agrees
uint64_t mix(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

void init_row(float* w, int dim, int64_t id, const Opt& o) {
  if (o.initial_range == 0.0f) {
    std::memset(w, 0, sizeof(float) * static_cast<size_t>(dim));
    return;
  }
  uint64_t s = mix(static_cast<uint64_t>(id) + 0x51a9b2c3d4e5f601ull);
  for (int i = 0; i < dim; ++i) {
    s = mix(s);
    float u = static_cast<float>(s >> 11) * (1.0f / 9007199254740992.0f);
    w[i] = (2.0f * u - 1.0f) * o.initial_range;
  }
}

void apply_rule(float* row, const float* g, int dim, const Opt& o) {
  float* w = row;
  switch (o.rule) {
    case Rule::kNaive: {
      for (int i = 0; i < dim; ++i) w[i] -= o.lr * g[i];
      break;
    }
    case Rule::kAdagrad: {
      float* g2 = row + dim;
      for (int i = 0; i < dim; ++i) {
        g2[i] += g[i] * g[i];
        w[i] -= o.lr * g[i] /
                (std::sqrt(g2[i] + o.initial_g2sum) + o.eps);
      }
      break;
    }
    case Rule::kAdam: {
      float* m = row + dim;
      float* v = row + 2 * dim;
      float& b1p = row[3 * dim];
      float& b2p = row[3 * dim + 1];
      b1p *= o.beta1;
      b2p *= o.beta2;
      for (int i = 0; i < dim; ++i) {
        m[i] = o.beta1 * m[i] + (1 - o.beta1) * g[i];
        v[i] = o.beta2 * v[i] + (1 - o.beta2) * g[i] * g[i];
        float mhat = m[i] / (1 - b1p);
        float vhat = v[i] / (1 - b2p);
        w[i] -= o.lr * mhat / (std::sqrt(vhat) + o.eps);
      }
      break;
    }
  }
}

struct SparseTable {
  int dim;
  Opt opt;
  int row_floats;
  std::unordered_map<int64_t, std::vector<float>> shard[kShards];
  std::mutex mu[kShards];

  std::vector<float>& row(int64_t id) {
    int s = static_cast<int>((static_cast<uint64_t>(id)) % kShards);
    auto& m = shard[s];
    auto it = m.find(id);
    if (it == m.end()) {
      std::vector<float> r(static_cast<size_t>(row_floats), 0.0f);
      init_row(r.data(), dim, id, opt);
      if (opt.rule == Rule::kAdam) {
        r[static_cast<size_t>(3 * dim)] = 1.0f;      // beta1_pow
        r[static_cast<size_t>(3 * dim) + 1] = 1.0f;  // beta2_pow
      }
      it = m.emplace(id, std::move(r)).first;
    }
    return it->second;
  }
};

Opt parse_opt(const char* name, float lr, float initial_range) {
  Opt o;
  o.lr = lr;
  o.initial_range = initial_range;
  std::string n(name ? name : "sgd");
  if (n == "adagrad")
    o.rule = Rule::kAdagrad;
  else if (n == "adam")
    o.rule = Rule::kAdam;
  else
    o.rule = Rule::kNaive;
  return o;
}

}  // namespace

extern "C" {

void* pst_create(int dim, const char* optimizer, float lr,
                 float initial_range) {
  auto* t = new SparseTable();
  t->dim = dim;
  t->opt = parse_opt(optimizer, lr, initial_range);
  t->row_floats = dim + slot_floats(t->opt.rule, dim);
  return t;
}

// gather rows for n ids into out (n x dim, row-major)
void pst_pull(void* h, const int64_t* ids, int n, float* out) {
  auto* t = static_cast<SparseTable*>(h);
  for (int i = 0; i < n; ++i) {
    int s = static_cast<int>(static_cast<uint64_t>(ids[i]) % kShards);
    std::lock_guard<std::mutex> lk(t->mu[s]);
    const auto& r = t->row(ids[i]);
    std::memcpy(out + static_cast<size_t>(i) * t->dim, r.data(),
                sizeof(float) * static_cast<size_t>(t->dim));
  }
}

// apply the SGD rule per id with its gradient row (n x dim); duplicate ids
// apply sequentially (the reference accumulates per occurrence too)
void pst_push(void* h, const int64_t* ids, int n, const float* grads) {
  auto* t = static_cast<SparseTable*>(h);
  for (int i = 0; i < n; ++i) {
    int s = static_cast<int>(static_cast<uint64_t>(ids[i]) % kShards);
    std::lock_guard<std::mutex> lk(t->mu[s]);
    auto& r = t->row(ids[i]);
    apply_rule(r.data(), grads + static_cast<size_t>(i) * t->dim, t->dim,
               t->opt);
  }
}

// w[id] += delta under the bucket lock (atomic geo-async merge; the
// reference geo table merges under its table lock too)
void pst_add(void* h, const int64_t* ids, int n, const float* deltas) {
  auto* t = static_cast<SparseTable*>(h);
  for (int i = 0; i < n; ++i) {
    int s = static_cast<int>(static_cast<uint64_t>(ids[i]) % kShards);
    std::lock_guard<std::mutex> lk(t->mu[s]);
    auto& r = t->row(ids[i]);
    const float* d = deltas + static_cast<size_t>(i) * t->dim;
    for (int j = 0; j < t->dim; ++j) r[static_cast<size_t>(j)] += d[j];
  }
}

// overwrite weights (no optimizer update) — load path
void pst_assign(void* h, const int64_t* ids, int n, const float* vals) {
  auto* t = static_cast<SparseTable*>(h);
  for (int i = 0; i < n; ++i) {
    int s = static_cast<int>(static_cast<uint64_t>(ids[i]) % kShards);
    std::lock_guard<std::mutex> lk(t->mu[s]);
    auto& r = t->row(ids[i]);
    std::memcpy(r.data(), vals + static_cast<size_t>(i) * t->dim,
                sizeof(float) * static_cast<size_t>(t->dim));
  }
}

long long pst_size(void* h) {
  auto* t = static_cast<SparseTable*>(h);
  long long n = 0;
  for (int s = 0; s < kShards; ++s) {
    std::lock_guard<std::mutex> lk(t->mu[s]);
    n += static_cast<long long>(t->shard[s].size());
  }
  return n;
}

// export all (id, w) pairs; ids/out sized by pst_size()*  — caller
// allocates.  Returns rows written.
long long pst_export(void* h, int64_t* ids, float* out, long long cap) {
  auto* t = static_cast<SparseTable*>(h);
  long long n = 0;
  for (int s = 0; s < kShards; ++s) {
    std::lock_guard<std::mutex> lk(t->mu[s]);
    for (auto& kv : t->shard[s]) {
      if (n >= cap) return n;
      ids[n] = kv.first;
      std::memcpy(out + static_cast<size_t>(n) * t->dim, kv.second.data(),
                  sizeof(float) * static_cast<size_t>(t->dim));
      ++n;
    }
  }
  return n;
}

// binary save/load: [int32 dim][int64 count]([int64 id][float w*dim])*
// (weights only — optimizer slots rebuild on demand, like the reference's
// converter-based save)
int pst_save(void* h, const char* path) {
  auto* t = static_cast<SparseTable*>(h);
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  int32_t dim = t->dim;
  // write a placeholder count, COUNT THE ROWS ACTUALLY WRITTEN under the
  // per-shard locks, then seek back and patch the header: a concurrent push
  // between a size() snapshot and the shard walk can otherwise make the
  // header disagree with the body (load would drop rows or fail)
  int64_t count = 0;
  std::fwrite(&dim, sizeof(dim), 1, f);
  long count_pos = std::ftell(f);
  std::fwrite(&count, sizeof(count), 1, f);
  for (int s = 0; s < kShards; ++s) {
    std::lock_guard<std::mutex> lk(t->mu[s]);
    for (auto& kv : t->shard[s]) {
      std::fwrite(&kv.first, sizeof(int64_t), 1, f);
      std::fwrite(kv.second.data(), sizeof(float),
                  static_cast<size_t>(dim), f);
      ++count;
    }
  }
  std::fseek(f, count_pos, SEEK_SET);
  std::fwrite(&count, sizeof(count), 1, f);
  std::fclose(f);
  return 0;
}

int pst_load(void* h, const char* path) {
  auto* t = static_cast<SparseTable*>(h);
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int32_t dim = 0;
  int64_t count = 0;
  if (std::fread(&dim, sizeof(dim), 1, f) != 1 || dim != t->dim ||
      std::fread(&count, sizeof(count), 1, f) != 1) {
    std::fclose(f);
    return -2;
  }
  std::vector<float> w(static_cast<size_t>(dim));
  for (int64_t i = 0; i < count; ++i) {
    int64_t id;
    if (std::fread(&id, sizeof(id), 1, f) != 1 ||
        std::fread(w.data(), sizeof(float), static_cast<size_t>(dim), f) !=
            static_cast<size_t>(dim)) {
      std::fclose(f);
      return -2;
    }
    pst_assign(h, &id, 1, w.data());
  }
  std::fclose(f);
  return 0;
}

void pst_destroy(void* h) { delete static_cast<SparseTable*>(h); }

// ---- dense table: one contiguous parameter block with the same rules ----

void* pdt_create(long long size, const char* optimizer, float lr) {
  // a dense table is one flat parameter block: a single row of `size`.
  // row_floats is int-indexed (adam slots reach 3*dim+2), so reject sizes
  // the int math cannot represent instead of silently wrapping.
  if (size <= 0 || size > ((1LL << 31) - 4) / 3) return nullptr;
  auto* t = new SparseTable();
  t->opt = parse_opt(optimizer, lr, 0.0f);
  t->dim = static_cast<int>(size);
  t->row_floats = t->dim + slot_floats(t->opt.rule, t->dim);
  int64_t id = 0;
  std::lock_guard<std::mutex> lk(t->mu[0]);
  t->shard[0].emplace(id, std::vector<float>(
      static_cast<size_t>(t->row_floats), 0.0f));
  if (t->opt.rule == Rule::kAdam) {
    auto& r = t->shard[0][0];
    r[static_cast<size_t>(3 * t->dim)] = 1.0f;
    r[static_cast<size_t>(3 * t->dim) + 1] = 1.0f;
  }
  return t;
}

void pdt_pull(void* h, float* out) {
  auto* t = static_cast<SparseTable*>(h);
  std::lock_guard<std::mutex> lk(t->mu[0]);
  std::memcpy(out, t->shard[0][0].data(),
              sizeof(float) * static_cast<size_t>(t->dim));
}

void pdt_push(void* h, const float* grad) {
  auto* t = static_cast<SparseTable*>(h);
  std::lock_guard<std::mutex> lk(t->mu[0]);
  apply_rule(t->shard[0][0].data(), grad, t->dim, t->opt);
}

void pdt_assign(void* h, const float* vals) {
  auto* t = static_cast<SparseTable*>(h);
  std::lock_guard<std::mutex> lk(t->mu[0]);
  std::memcpy(t->shard[0][0].data(), vals,
              sizeof(float) * static_cast<size_t>(t->dim));
}

void pdt_destroy(void* h) { delete static_cast<SparseTable*>(h); }

}  // extern "C"
