"""hapi callbacks (reference: python/paddle/hapi/callbacks.py — Callback,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL) +
ObsCallback, the training-loop hookup for paddle_tpu.obs telemetry."""

from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and self.log_freq and step % self.log_freq == 0:
            loss = (logs or {}).get("loss")
            print(f"Epoch {self.epoch} step {step}: loss={loss}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch} done in {dt:.1f}s: {logs}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.wait = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda new, best: new > best + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda new, best: new < best - self.min_delta
            self.best = np.inf
        if baseline is not None:
            self.best = baseline

    def _value(self, logs):
        v = (logs or {}).get(self.monitor)
        if v is None:
            return None
        v = v[0] if isinstance(v, (list, tuple)) else v
        return float(v)

    def on_epoch_end(self, epoch, logs=None):
        v = self._value(logs)
        if v is None:
            return
        if self.better(v, self.best):
            self.best = v
            self.wait = 0
            save_dir = getattr(self.model, "_save_dir", None)
            if self.save_best_model and save_dir:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True


class ObsCallback(Callback):
    """Span-trace + metrics + recompile-sentinel instrumentation for a
    training loop (paddle_tpu.obs on the hapi callback protocol).

    Per train batch: opens step lane N (`tracer.step_mark`), wraps the
    step in a `train_step` span — fenced on `fence_of(logs)` when given,
    so the span times the device compute rather than the async enqueue —
    records the step time into the `train_step_seconds` histogram, and
    runs the recompile sentinel (`watch(name, jitted_fn)` targets; a
    post-warmup cache miss raises RecompileWarning + a tracer event).
    On train end: exports the chrome trace to `export_path` if set.

    Works under `Model.fit(callbacks=[...])` or driven manually around
    any step loop (examples/train_llama.py does the latter)."""

    def __init__(self, tracer=None, registry=None, export_path=None,
                 fence_of=None):
        super().__init__()
        from ..obs import metrics as obs_metrics
        from ..obs import mfu as obs_mfu
        from ..obs import trace as obs_trace

        self.tracer = tracer if tracer is not None else obs_trace.get_tracer()
        self.registry = registry if registry is not None \
            else obs_metrics.Registry()
        self.export_path = export_path
        self.fence_of = fence_of
        self.sentinel = obs_mfu.RecompileSentinel(
            tracer=self.tracer, registry=self.registry)
        self._h_step = self.registry.histogram(
            "train_step_seconds", "wall time per train batch (fenced)")
        self._span = None
        self._was_enabled = None

    def watch(self, name, jitted_fn) -> "ObsCallback":
        """Register a jitted target with the recompile sentinel."""
        self.sentinel.watch(name, jitted_fn)
        return self

    def on_train_begin(self, logs=None):
        self._was_enabled = self.tracer.enabled
        self.tracer.enable()

    def on_train_batch_begin(self, step, logs=None):
        self.tracer.step_mark(step)
        self._span = self.tracer.span("train_step", step=step)
        self._span.__enter__()
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self._span is None:
            return
        # fence BEFORE timing: histogram and span must both cover the
        # device compute, not the async enqueue (works with the tracer
        # disabled too — the histogram is always live)
        fence = self.fence_of(logs) if self.fence_of and logs else None
        if fence is not None:
            try:
                import jax

                jax.block_until_ready(fence)
            except Exception:  # noqa: BLE001 — fencing must not kill
                pass           # the train loop
        self._h_step.observe(time.perf_counter() - self._t0)
        self._span.__exit__(None, None, None)
        self._span = None
        self.sentinel.check()

    def on_train_end(self, logs=None):
        if self.export_path:
            self.tracer.export_chrome(self.export_path)
        if self._was_enabled is False:
            self.tracer.disable()

    def step_summary(self) -> dict:
        """{mean_step_s, p50_step_s, p99_step_s, steps} over the recent
        raw-sample window — what runtime-MFU reports consume."""
        from ..obs import metrics as obs_metrics

        samples = self._h_step.samples()
        return {
            "steps": len(samples),
            "mean_step_s": (sum(samples) / len(samples)) if samples else 0.0,
            "p50_step_s": obs_metrics.percentile(samples, 0.5),
            "p99_step_s": obs_metrics.percentile(samples, 0.99),
        }


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each epoch/step (callbacks.py parity)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s:
            s.step()
