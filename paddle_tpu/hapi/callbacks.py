"""hapi callbacks (reference: python/paddle/hapi/callbacks.py — Callback,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL)."""

from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and self.log_freq and step % self.log_freq == 0:
            loss = (logs or {}).get("loss")
            print(f"Epoch {self.epoch} step {step}: loss={loss}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch} done in {dt:.1f}s: {logs}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.wait = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda new, best: new > best + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda new, best: new < best - self.min_delta
            self.best = np.inf
        if baseline is not None:
            self.best = baseline

    def _value(self, logs):
        v = (logs or {}).get(self.monitor)
        if v is None:
            return None
        v = v[0] if isinstance(v, (list, tuple)) else v
        return float(v)

    def on_epoch_end(self, epoch, logs=None):
        v = self._value(logs)
        if v is None:
            return
        if self.better(v, self.best):
            self.best = v
            self.wait = 0
            save_dir = getattr(self.model, "_save_dir", None)
            if self.save_best_model and save_dir:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each epoch/step (callbacks.py parity)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s:
            s.step()
