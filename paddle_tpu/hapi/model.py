"""High-level paddle.Model (reference: python/paddle/hapi/model.py:1048 Model,
fit at :1750) — prepare/fit/evaluate/predict/save/load over an nn.Layer.

TPU-native: the default train path is ONE fused XLA step per batch
(jit.TrainStep: forward+backward+update, donated buffers); per-batch metrics,
gradient accumulation and AMP contexts fall back to the eager tape step.
Inputs batch through paddle_tpu.io.DataLoader; device transfer is implicit in
jnp (device_put on first op).  The dygraph/static dual engine of the reference
collapses — XLA is always the executor.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence

import numpy as np

from .. import serialization
from ..metric import Metric
from ..tensor import Tensor, to_tensor
from . import callbacks as cbs


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._save_dir = None
        self.stop_training = False

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        ms = _to_list(metrics)
        for m in ms:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} must be a paddle_tpu.metric.Metric")
        self._metrics = ms
        # a new optimizer/loss invalidates any fused step built for the old
        self._jit_step = None
        self._jit_step_nin = None
        return self

    # -- single-batch ops (train_batch hapi parity) ------------------------
    def train_batch(self, inputs, labels=None, update=True, loss_scale=1.0):
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        # hot path: one fused XLA step (jit.TrainStep) whenever the eager
        # machinery isn't needed — no per-batch metrics over outputs, no
        # gradient accumulation, no AMP context.  Metrics/accumulation fall
        # back to the tape step below.
        from .. import framework as _fw

        eligible = (update and loss_scale == 1.0 and not self._metrics
                    and _fw.get_state().amp_state is None)
        if eligible:
            step = self._fused_step(len(inputs))
            if step is not None:
                loss = step(*[_as_tensor(x) for x in inputs + labels])
                return [float(np.asarray(getattr(loss, "data", loss)))]
        elif getattr(self, "_jit_step", None):
            # the fused step owns the optimizer moments; silently switching
            # to the eager path would restart Adam/momentum state mid-run
            raise RuntimeError(
                "this Model already trained with the fused step; cannot mix "
                "in eager batches (metrics/grad-accumulation/AMP) mid-run — "
                "call prepare() again to reset, or set those options before "
                "the first fit()")
        outputs = self.network(*[_as_tensor(x) for x in inputs])
        losses = self._compute_loss(outputs, labels)
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        if loss_scale != 1.0:
            total = total * loss_scale  # grad accumulation: mean over micro-batches
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = [float(np.asarray(l.data)) for l in losses]
        m_res = self._update_metrics(outputs, labels)
        return (metrics, m_res) if m_res else metrics

    def _fused_step(self, n_in):
        """Build (once) a jit.TrainStep over network+loss+optimizer.

        NB: the fused step owns a functional optimizer state; a fit() that
        mixes fused and eager batches would desync them, which is why every
        eligibility condition is checked per batch above."""
        cached = getattr(self, "_jit_step", None)
        if cached is not None:
            if getattr(self, "_jit_step_nin", None) != n_in and cached:
                raise RuntimeError(
                    "input arity changed after fused training began; "
                    "re-prepare() the Model to rebuild the step")
            return cached or None
        from .. import jit

        loss_obj = self._loss
        if loss_obj is None:
            raise RuntimeError("call prepare(loss=...) before training")

        def loss_fn(model, *batch):
            outs = _to_list(model(*batch[:n_in]))
            losses = _to_list(loss_obj(*(outs + list(batch[n_in:]))))
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            return total

        try:
            self._jit_step = jit.TrainStep(self.network, loss_fn,
                                           self._optimizer)
        except Exception:  # noqa: BLE001 — exotic models keep the eager path
            self._jit_step = False
        self._jit_step_nin = n_in
        return self._jit_step or None

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..autograd import no_grad

        with no_grad():
            inputs = _to_list(inputs)
            labels = _to_list(labels)
            outputs = self.network(*[_as_tensor(x) for x in inputs])
            losses = self._compute_loss(outputs, labels) if self._loss else []
            metrics = [float(np.asarray(l.data)) for l in losses]
            m_res = self._update_metrics(outputs, labels)
        return (metrics, m_res) if m_res else metrics

    def predict_batch(self, inputs):
        self.network.eval()
        from ..autograd import no_grad

        with no_grad():
            inputs = _to_list(inputs)
            outputs = self.network(*[_as_tensor(x) for x in inputs])
        return [np.asarray(o.data) if isinstance(o, Tensor) else np.asarray(o)
                for o in _to_list(outputs)]

    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) before training")
        res = self._loss(*(outs + [_as_tensor(l) for l in labels]))
        return _to_list(res)

    def _update_metrics(self, outputs, labels):
        res = {}
        outs = _to_list(outputs)
        for m in self._metrics:
            args = m.compute(*(outs + [_as_tensor(l) for l in labels])) \
                if hasattr(m, "compute") and m.compute is not None else outs
            m.update(*[np.asarray(getattr(a, "data", a)) for a in _to_list(args)])
            res[m.name() if callable(getattr(m, "name", None)) else str(m)] = \
                m.accumulate()
        return res

    # -- loops -------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        train_loader = _as_loader(train_data, batch_size, shuffle, drop_last,
                                  num_workers)
        eval_loader = (_as_loader(eval_data, batch_size, False, False,
                                  num_workers) if eval_data is not None else None)
        cblist = cbs.CallbackList(_to_list(callbacks) or
                                  ([cbs.ProgBarLogger(log_freq, verbose)]))
        cblist.set_model(self)
        self._save_dir = save_dir  # callbacks (EarlyStopping best-model) use it
        cblist.on_train_begin()
        history = {"loss": []}
        step_count = 0
        for epoch in range(epochs):
            cblist.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                xs, ys = _split_batch(batch)
                logs = {"step": step}
                cblist.on_train_batch_begin(step, logs)
                # gradient accumulation: step the optimizer every N batches
                update = (step + 1) % accumulate_grad_batches == 0
                out = self.train_batch(xs, ys, update=update,
                                       loss_scale=1.0 / accumulate_grad_batches)
                loss_vals = out[0] if isinstance(out, tuple) else out
                logs["loss"] = loss_vals
                if isinstance(out, tuple):
                    logs.update(out[1])
                cblist.on_train_batch_end(step, logs)
                history["loss"].append(loss_vals[0])
                step_count += 1
                if num_iters is not None and step_count >= num_iters:
                    break
            # flush a trailing partial accumulation so its gradients neither
            # leak into the next epoch nor get dropped at train end
            if accumulate_grad_batches > 1 and \
                    (step + 1) % accumulate_grad_batches != 0:
                self._optimizer.step()
                self._optimizer.clear_grad()
            epoch_logs = dict(logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_loader, verbose=0)
                epoch_logs.update({f"eval_{k}": v for k, v in eval_res.items()})
            cblist.on_epoch_end(epoch, epoch_logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training or (num_iters is not None
                                      and step_count >= num_iters):
                break
        cblist.on_train_end()
        if save_dir:
            self.save(os.path.join(save_dir, "final"))
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = _as_loader(eval_data, batch_size, False, False, num_workers)
        cblist = cbs.CallbackList(_to_list(callbacks))
        cblist.set_model(self)
        cblist.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        seen = 0
        for batch in loader:
            xs, ys = _split_batch(batch)
            out = self.eval_batch(xs, ys)
            loss_vals = out[0] if isinstance(out, tuple) else out
            if loss_vals:
                losses.append(loss_vals[0])
            # count actual samples (loader batch size may differ from the arg)
            first = xs[0] if xs else None
            seen += (len(first) if first is not None and hasattr(first, "__len__")
                     else batch_size)
            if num_samples is not None and seen >= num_samples:
                break
        res = {}
        if losses:
            res["loss"] = [float(np.mean(losses))]
        for m in self._metrics:
            res[m.name() if callable(getattr(m, "name", None)) else str(m)] = \
                m.accumulate()
        cblist.on_eval_end(res)
        return res

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        # with declared input specs, only that many leading elements are fed
        # (reference hapi uses self._inputs the same way); otherwise the whole
        # batch tuple is treated as inputs
        n_in = len(_to_list(self._inputs)) if self._inputs is not None else None
        outs = []
        for batch in loader:
            xs, _ = _split_batch(batch, labeled=False)
            if n_in is not None:
                xs = xs[:n_in]
            outs.append(self.predict_batch(xs))
        n_out = len(outs[0]) if outs else 0
        grouped = [[o[i] for o in outs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g) for g in grouped]
        return grouped

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        serialization.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            serialization.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = serialization.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)
                and hasattr(self._optimizer, "set_state_dict")):
            self._optimizer.set_state_dict(serialization.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtypes=dtype)


def _as_tensor(x):
    return x if isinstance(x, Tensor) else to_tensor(np.asarray(x))


def _split_batch(batch, labeled=True):
    """(x, y) | [x, y] | x -> (inputs list, labels list)."""
    if isinstance(batch, (list, tuple)):
        if not labeled or len(batch) == 1:
            return _to_list(batch if len(batch) > 1 else batch[0]), []
        return _to_list(batch[0]), _to_list(batch[1])
    return [batch], []


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    from ..io import DataLoader, Dataset

    if data is None:
        raise ValueError("data is required")
    if isinstance(data, DataLoader):
        return data
    if hasattr(data, "__getitem__") and hasattr(data, "__len__"):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    return data  # assume iterable of batches
