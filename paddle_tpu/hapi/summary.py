"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""

from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter-count summary.  Returns {'total_params': N, 'trainable_params': N}
    and prints a per-layer table like the reference."""
    rows = []

    def walk(layer, prefix):
        own = 0
        for name, p in layer._parameters.items():
            if p is None:
                continue
            own += int(np.prod(p.shape))
        if own:
            rows.append((prefix or type(layer).__name__,
                         type(layer).__name__, own))
        for name, sub in layer._sub_layers.items():
            walk(sub, f"{prefix}.{name}" if prefix else name)

    walk(net, "")
    total = sum(r[2] for r in rows)
    trainable = 0
    for p in net.parameters():
        if getattr(p, "trainable", True):
            trainable += int(np.prod(p.shape))
    w = max([len(r[0]) for r in rows] + [10])
    print(f"{'Layer':{w}}  {'Type':18}  Params")
    print("-" * (w + 30))
    for name, t, n in rows:
        print(f"{name:{w}}  {t:18}  {n:,}")
    print("-" * (w + 30))
    print(f"Total params: {total:,}")
    return {"total_params": total, "trainable_params": trainable}
