from .model import Model  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
)
from . import summary as _summary_mod  # noqa: F401
from .summary import summary  # noqa: F401
