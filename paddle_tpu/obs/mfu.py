"""Measured-vs-static: runtime MFU, cost-model ratio, recompile sentinel.

Graph Doctor's cost pass (analysis/cost.py) counts the FLOPs a jitted
target *should* execute; the tracer measures how long it *did* take.
This module joins the two, per jitted target:

  * `runtime_report(...)` -> {flops_per_step, predicted_step_s,
    measured_step_s, runtime_mfu, cost_model_ratio}.  `runtime_mfu` is
    achieved FLOP/s over the chip's peak (jaxpr-counted FLOPs, so it can
    differ from a 6N-formula MFU — that difference is signal, not
    error).  `cost_model_ratio` is measured / predicted step time: ~1
    means the static model is trustworthy for placement decisions, >>1
    means the target is nowhere near compute-bound (or the model is
    missing a term) — the gate the ROADMAP's autotuner/mega-kernel work
    wants before trusting static numbers.
  * `RecompileSentinel` watches jitted fns' compile caches and warns
    (python warning + tracer instant event + registry counter) when a
    target recompiles AFTER warmup — the runtime companion to the
    static RECOMPILE_* lints: those predict hazards, this catches the
    ones that actually fire in production.
"""

from __future__ import annotations

import warnings
from typing import Callable, Dict, Optional

__all__ = ["PEAK_FLOPS_BY_KIND", "device_peak_flops", "runtime_report",
           "phase_runtime_report", "RecompileSentinel", "RecompileWarning"]

# bf16 peak FLOP/s per chip; ordered most-specific-first for substring
# match on device_kind (bench.py delegates here — one table, one truth)
PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12), ("v6", 918e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5litepod", 197e12), ("v5p", 459e12), ("v5", 459e12), ("v4", 275e12),
)


def device_peak_flops(device=None) -> float:
    """Peak bf16 FLOP/s of `device` (default: jax.devices()[0]).
    Returns 0.0 for CPU — MFU is not meaningful there and callers must
    treat 0 as "no peak known"."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_FLOPS_BY_KIND:
        if k in kind:
            return v
    if getattr(device, "platform", None) == "tpu":
        return 459e12  # assume v5p-class
    return 0.0


def runtime_report(measured_step_s: float, flops_per_step: float,
                   peak_flops: Optional[float] = None,
                   device=None) -> dict:
    """Join one measured step time with its static FLOPs count.

    With no known peak (CPU): runtime_mfu = 0.0 and cost_model_ratio =
    None rather than a fabricated number."""
    if peak_flops is None:
        peak_flops = device_peak_flops(device)
    measured_step_s = float(measured_step_s)
    flops_per_step = float(flops_per_step)
    out = {
        "flops_per_step": flops_per_step,
        "measured_step_s": measured_step_s,
        "predicted_step_s": None,
        "runtime_mfu": 0.0,
        "cost_model_ratio": None,
    }
    if peak_flops > 0 and measured_step_s > 0:
        predicted = flops_per_step / peak_flops
        out["predicted_step_s"] = predicted
        out["runtime_mfu"] = flops_per_step / measured_step_s / peak_flops
        if predicted > 0:
            out["cost_model_ratio"] = measured_step_s / predicted
    return out


def phase_runtime_report(phase_times_s: Dict[str, float],
                         phase_flops: Dict[str, float],
                         peak_flops: Optional[float] = None,
                         device=None) -> Dict[str, dict]:
    """Per-PHASE measured-vs-static join: `runtime_report` for every
    phase that has both a measured time and a static FLOPs count —
    `cost_model_ratio` stops being a whole-step verdict and becomes a
    per-phase one (the ragged dispatch can be model-faithful while the
    host-side commit pass isn't priced at all).  Phases with no static
    entry are skipped: the cost model prices device dispatches, not
    scheduler host time, and a fabricated 0-FLOPs ratio would read as
    "infinitely slower than predicted"."""
    if peak_flops is None:
        peak_flops = device_peak_flops(device)
    return {
        phase: runtime_report(phase_times_s[phase], flops,
                              peak_flops=peak_flops)
        for phase, flops in phase_flops.items()
        if phase in phase_times_s
    }


def static_flops(fn, *args, **kwargs) -> float:
    """jaxpr-counted FLOPs of one call of `fn(*args)` (the cost pass's
    roll-up; nothing executes)."""
    from ..analysis import cost as cost_lib

    return cost_lib.total_flops(fn, *args, **kwargs)


class RecompileWarning(UserWarning):
    """A watched jitted target recompiled after warmup."""


def _cache_size(fn) -> Optional[int]:
    """Compile-cache entry count of a jitted fn, or None when this jax
    doesn't expose it (sentinel goes inert, never wrong)."""
    try:
        get = getattr(fn, "_cache_size", None)
        return None if get is None else int(get())
    except Exception:  # noqa: BLE001
        return None


class _Watch:
    __slots__ = ("fn", "baseline", "recompiles")

    def __init__(self, fn):
        self.fn = fn
        self.baseline: Optional[int] = None
        self.recompiles = 0


class RecompileSentinel:
    """Counts compile-cache misses per watched jitted fn.

    `watch(name, jitted_fn)` registers a target; call `check()` once per
    step.  The first check snapshots the cache size as the warmup
    baseline (the initial compile is expected); any LATER growth counts
    as a recompile and emits a RecompileWarning, a tracer instant event
    ("recompile"), and bumps the registry counter
    `recompiles_total{fn=...}`.  Steady-state steps are silent — 50 warm
    steps must not produce a single event (pinned by tests/test_obs.py).
    """

    def __init__(self, tracer=None, registry=None):
        self._watches: Dict[str, _Watch] = {}
        self.tracer = tracer
        self.registry = registry

    def watch(self, name: str, jitted_fn: Callable) -> "RecompileSentinel":
        self._watches[name] = _Watch(jitted_fn)
        return self

    def check(self) -> Dict[str, int]:
        """One step boundary: compare each watched fn's cache size to its
        baseline; fire on growth.  Returns {name: new_misses_this_check}.
        """
        fired = {}
        for name, w in self._watches.items():
            n = _cache_size(w.fn)
            if n is None:
                continue
            if w.baseline is None:
                w.baseline = n         # warmup compile(s): expected
                continue
            if n > w.baseline:
                miss = n - w.baseline
                w.baseline = n
                w.recompiles += miss
                fired[name] = miss
                self._emit(name, miss, w.recompiles)
        return fired

    def _emit(self, name: str, miss: int, total: int) -> None:
        warnings.warn(
            f"jitted target {name!r} recompiled after warmup "
            f"(+{miss} cache entr{'y' if miss == 1 else 'ies'}, "
            f"{total} total): a shape/dtype/static-arg changed mid-run — "
            f"see the RECOMPILE_* lints for the static-side hazard list",
            RecompileWarning, stacklevel=3)
        if self.tracer is not None:
            self.tracer.instant("recompile", fn=name, misses=miss,
                                total=total)
        if self.registry is not None:
            self.registry.counter(
                "recompiles_total",
                "post-warmup compile-cache misses per jitted target",
                labels={"fn": name}).inc(miss)

    def counts(self) -> Dict[str, int]:
        """{name: post-warmup recompiles so far} for every watched fn."""
        return {name: w.recompiles for name, w in self._watches.items()}
