"""Per-step phase profiler: where inside a STEP did the time go.

The span tracer (obs.trace) answers "what spans ran"; the request
registry answers "where did request X go".  Neither answers the
question the autotuner, prefix-reuse, and mega-kernel roadmap items
consume: *what share of a steady-state engine step is scheduler host
time vs ragged dispatch vs sampling vs commit* — and how does the
dispatch's measured time compare to the static cost model, per shape
class.  This module is that attribution layer:

  * `StepProfiler.step()` opens one step frame; `phase(name)` context
    managers inside it record SELF time per phase (a nested phase's
    duration is subtracted from its parent, so `verify` inside
    `commit` and `swap` inside `schedule` never double-count and the
    per-step shares sum to ~1.0).  Whatever the phases did not cover
    lands in the synthetic `other` phase.
  * phases accept a `fence=`-style `.fence(arrays)` exactly like
    tracer spans: jax dispatch is async, and the `dispatch` phase must
    time the compute, not the enqueue.
  * phases may carry a `shape_class` tag — the dispatch phase is keyed
    by its batch geometry (`T48xS4` = 48 query rows, 4 spans), which
    is the key a per-generation kernel autotuner caches winners under.
  * frames land in a bounded rolling window; `report()` aggregates
    per-phase totals/means/percentiles and SHARES over that window
    (the `/stats` surface), `record_window()` hands the raw per-step
    frames to the anomaly watchdog, and `cost_join(phase, flops)`
    joins a phase's measured mean against the static cost model via
    `obs.mfu.runtime_report` — `cost_model_ratio` per phase per shape
    class instead of whole-step only.

Disabled cost ~ zero: `step()`/`phase()` return shared no-op context
managers behind one branch, so the instrumentation lives permanently
inside `LLMEngine.step()`.  Enabled cost is a few `perf_counter`
reads and dict adds per step — bench.py `extra.obs_overhead` pins the
whole layer (profiler + pool telemetry + watchdog) under 2% of decode
ITL.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from . import metrics as obs_metrics

__all__ = ["StepProfiler", "ENGINE_PHASES"]

# the engine's step decomposition, in execution order.  "other" is the
# synthetic remainder (step total minus every recorded phase) — a
# growing "other" share means the step loop gained un-attributed work.
ENGINE_PHASES = ("schedule", "build_batch", "dispatch", "sample",
                 "verify", "commit", "swap", "transfer", "other")


class _NoopPhase:
    """Shared do-nothing frame/phase while the profiler is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, value):
        return self


_NOOP = _NoopPhase()


class _Frame:
    """One step's accounting: per-phase self time + shape-class time."""

    __slots__ = ("t0", "child_s", "phases", "classes")

    def __init__(self, t0: float):
        self.t0 = t0
        self.child_s = 0.0                  # time covered by phases
        self.phases: Dict[str, float] = {}
        self.classes: Dict[tuple, float] = {}


class _Phase:
    __slots__ = ("_prof", "name", "shape_class", "_t0", "_fence",
                 "_child_s")

    def __init__(self, prof: "StepProfiler", name: str,
                 shape_class: Optional[str]):
        self._prof = prof
        self.name = name
        self.shape_class = shape_class
        self._fence = None
        self._child_s = 0.0

    def fence(self, value) -> "_Phase":
        """Block on `value` before the closing timestamp so the phase
        covers the device compute, not the enqueue (same contract as
        tracer spans; a no-op on CPU interpret paths)."""
        self._fence = value
        return self

    def __enter__(self):
        self._prof._stack().append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._fence is not None:
            try:
                import jax

                jax.block_until_ready(self._fence)
            except Exception:  # noqa: BLE001 — a deleted/donated buffer
                pass           # must not turn a timing into a crash
        dur = time.perf_counter() - self._t0
        prof = self._prof
        stack = prof._stack()
        if stack and stack[-1] is self:
            stack.pop()
        # full duration charges the parent's child account; SELF time
        # (minus nested phases) lands on this phase — shares stay
        # disjoint however phases nest
        parent = stack[-1] if stack else None
        if parent is not None:
            parent._child_s += dur
        self_s = max(0.0, dur - self._child_s)
        frame = prof._frame()
        if frame is not None:
            frame.child_s += 0.0 if parent is not None else dur
            frame.phases[self.name] = \
                frame.phases.get(self.name, 0.0) + self_s
            if self.shape_class is not None:
                key = (self.name, str(self.shape_class))
                frame.classes[key] = frame.classes.get(key, 0.0) + self_s
        return False


class _StepCtx:
    __slots__ = ("_prof", "record")

    def __init__(self, prof: "StepProfiler"):
        self._prof = prof
        self.record = None      # filled on exit: the frame's dict form

    def __enter__(self):
        self._prof._open_frame()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.record = self._prof._close_frame()
        return False


class StepProfiler:
    """Rolling per-step phase attribution.  One per engine (the frame
    stack is per-thread, so a shared instance would still attribute
    correctly, but the window would mix engines)."""

    def __init__(self, window: int = 256, enabled: bool = True):
        self.enabled = bool(enabled)
        self._records: collections.deque = collections.deque(
            maxlen=int(window))
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.steps_total = 0

    # -- control ------------------------------------------------------------

    def enable(self) -> "StepProfiler":
        self.enabled = True
        return self

    def disable(self) -> "StepProfiler":
        self.enabled = False
        return self

    def reset_window(self) -> "StepProfiler":
        """Drop the rolling window (steps_total keeps counting).  Benches
        call this after warmup so one compile-bearing step cannot skew
        the per-phase means of a short measurement window."""
        with self._lock:
            self._records.clear()
        return self

    # -- recording ----------------------------------------------------------

    def _stack(self) -> List[_Phase]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _frame(self) -> Optional[_Frame]:
        return getattr(self._tls, "frame", None)

    def _open_frame(self) -> None:
        self._tls.frame = _Frame(time.perf_counter())
        self._tls.stack = []

    def _close_frame(self) -> Optional[dict]:
        frame = self._frame()
        if frame is None:
            return None
        self._tls.frame = None
        total = time.perf_counter() - frame.t0
        other = max(0.0, total - frame.child_s)
        if other > 0.0:
            frame.phases["other"] = \
                frame.phases.get("other", 0.0) + other
        rec = {"t": time.perf_counter(), "total_s": total,
               "phases": frame.phases, "classes": frame.classes}
        with self._lock:
            self._records.append(rec)
            self.steps_total += 1
        return rec

    def step(self):
        """Context manager for ONE engine step; every `phase()` entered
        inside it lands on this step's frame.  `.record` holds the
        frame dict after exit (the watchdog's input)."""
        if not self.enabled:
            return _NOOP
        return _StepCtx(self)

    def phase(self, name: str, shape_class: Optional[str] = None):
        """Context manager for one phase inside the current step.  A
        phase entered with no open step frame records nothing (still a
        valid no-op).  Phases nest: a child's time is charged to the
        child only."""
        if not self.enabled or self._frame() is None:
            return _NOOP
        return _Phase(self, name, shape_class)

    # -- reading ------------------------------------------------------------

    def record_window(self) -> List[dict]:
        """The raw per-step frames in the rolling window (oldest
        first) — the anomaly watchdog's baseline feed."""
        with self._lock:
            return list(self._records)

    def report(self) -> dict:
        """Windowed aggregate — the `/stats` phase table:
        {steps_total, window, step: {count, mean_s, p50_s, p99_s},
        phases: {name: {count, total_s, mean_s, share}},
        shape_classes: {phase: {cls: {count, total_s, mean_s}}}}.
        `share` = phase total / step total over the window; shares sum
        to ~1.0 because nested phases record self time only."""
        recs = self.record_window()
        totals = sorted(r["total_s"] for r in recs)
        out = {
            "steps_total": self.steps_total,
            "window": len(recs),
            "step": {
                "count": len(recs),
                "mean_s": (sum(totals) / len(totals)) if totals else 0.0,
                "p50_s": obs_metrics.percentile(totals, 0.50),
                "p99_s": obs_metrics.percentile(totals, 0.99),
            },
            "phases": {},
            "shape_classes": {},
        }
        window_total = sum(totals)
        agg: Dict[str, List[float]] = {}
        cls_agg: Dict[tuple, List[float]] = {}
        for r in recs:
            for name, s in r["phases"].items():
                agg.setdefault(name, []).append(s)
            for key, s in r["classes"].items():
                cls_agg.setdefault(key, []).append(s)
        for name, vals in agg.items():
            tot = sum(vals)
            out["phases"][name] = {
                "count": len(vals),
                "total_s": tot,
                "mean_s": tot / len(vals),
                "share": (tot / window_total) if window_total else 0.0,
            }
        for (name, cls), vals in cls_agg.items():
            tot = sum(vals)
            out["shape_classes"].setdefault(name, {})[cls] = {
                "count": len(vals),
                "total_s": tot,
                "mean_s": tot / len(vals),
            }
        return out

    def share(self, name: str) -> float:
        """One phase's windowed time share (the per-phase gauges read
        this lazily at scrape time)."""
        total = 0.0
        phase = 0.0
        for r in self.record_window():
            total += r["total_s"]
            phase += r["phases"].get(name, 0.0)
        return (phase / total) if total else 0.0

    def mean_s(self, name: str) -> float:
        vals = [r["phases"][name] for r in self.record_window()
                if name in r["phases"]]
        return (sum(vals) / len(vals)) if vals else 0.0

    def cost_join(self, phase: str, flops: float,
                  peak_flops: Optional[float] = None,
                  device=None) -> Dict[str, dict]:
        """Join one phase's measured mean time against its static FLOPs
        count, PER SHAPE CLASS: {shape_class: runtime_report dict} —
        `cost_model_ratio` per (phase, shape class) instead of per
        whole step.  Phases recorded without a shape class key under
        "".  This is the table the per-generation autotuner reads:
        measured time by shape class, calibrated against the static
        model's prediction."""
        from . import mfu as obs_mfu

        by_cls: Dict[str, List[float]] = {}
        for r in self.record_window():
            untagged = r["phases"].get(phase, 0.0)
            for (name, cls), s in r["classes"].items():
                if name != phase:
                    continue
                by_cls.setdefault(cls, []).append(s)
                untagged -= s
            if phase in r["phases"] and untagged > 1e-12:
                by_cls.setdefault("", []).append(untagged)
        out = {}
        for cls, vals in by_cls.items():
            measured = sum(vals) / len(vals)
            out[cls] = obs_mfu.runtime_report(
                measured, flops, peak_flops=peak_flops, device=device)
        return out

    def register_gauges(self, registry: obs_metrics.Registry,
                        prefix: str = "llm_step",
                        phases=ENGINE_PHASES) -> "StepProfiler":
        """Expose the windowed phase table on a Prometheus registry:
        `<prefix>_seconds` (mean step time), `<prefix>_phase_seconds` /
        `<prefix>_phase_share` per {phase=...} label.  Gauges read
        lazily at scrape time — the step thread never pushes."""
        registry.gauge(
            f"{prefix}_seconds",
            "mean engine step wall time over the profiler window"
        ).set_function(lambda: (
            (lambda recs: sum(r["total_s"] for r in recs) / len(recs)
             if recs else 0.0)(self.record_window())))
        for name in phases:
            registry.gauge(
                f"{prefix}_phase_seconds",
                "mean SELF time of one step phase over the window",
                labels={"phase": name}
            ).set_function(lambda n=name: self.mean_s(n))
            registry.gauge(
                f"{prefix}_phase_share",
                "phase share of total step time over the window "
                "(self-time attribution: shares sum to ~1)",
                labels={"phase": name}
            ).set_function(lambda n=name: self.share(n))
        return self
