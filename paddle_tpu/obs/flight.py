"""Flight recorder: a black-box that dumps on crash, loadable later.

A soak run that dies at 3am leaves you a stack trace and nothing else —
the spans, counters, and engine state that explain the death lived in
the dead process.  The flight recorder is the rolling black-box: it
holds references to the obs sources (span tracer ring, metrics
registry, request-timeline registry, an engine-state digest callable)
and, when something dies, writes ONE JSON dump of all of them —
atomically (tmp + os.replace), never raising into the failure path that
triggered it.

Dump triggers, wired where the failures happen:

  * step-thread death — `LLMEngine._loop`'s BaseException path dumps
    before the thread exits (the InjectedCrash / segfaulting-kernel
    shape);
  * replica death / health ejection — the Router dumps the dead or
    ejected replica's recorder BEFORE tearing the engine down, so the
    digest shows the pre-crash slots, not the post-shutdown rubble;
  * invariant violation — `faults.check_invariants` dumps when a chaos
    schedule finds a leak, capturing the state that leaked;
  * SIGTERM — `install_sigterm()` chains a dump in front of the
    previous handler (opt-in: tools arm it, libraries never touch
    process signal state).

`load_dump(path)` reads a dump back and validates the schema — the
chaos tools (`--flight-dir`) fail a soak when a crash produced no
loadable dump, which keeps the recorder honest under the exact storms
it exists for.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Callable, List, Optional

__all__ = ["FlightRecorder", "load_dump", "install_sigterm",
           "SCHEMA"]

SCHEMA = "paddle_tpu.flight/v1"

# the keys every dump carries; load_dump validates them so a truncated
# or foreign file fails loudly instead of half-parsing
_REQUIRED = ("schema", "reason", "name", "wall_time", "spans", "metrics",
             "engine", "requests", "error")


class FlightRecorder:
    """Rolling black-box over one engine's obs sources.

    dir: dump directory (created on first dump).  None = in-memory only:
    `dump()` still snapshots into `self.last` (tests and embedders read
    it) but writes nothing.
    name: stamped into dumps and filenames (the router uses replica ids).
    max_spans / max_requests: bound the dump size — the most recent
    window, which is the one that explains a crash.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(self, dir: Optional[str] = None, name: str = "engine",
                 max_spans: int = 2048, max_requests: int = 32):
        self.dir = dir
        self.name = str(name)
        self.max_spans = int(max_spans)
        self.max_requests = int(max_requests)
        self._tracer = None
        self._registry = None
        self._reqtrace = None
        self._state_fn: Optional[Callable[[], dict]] = None
        self.last: Optional[dict] = None      # most recent snapshot
        self.dumps: List[str] = []            # paths written (dir mode)
        self._lock = threading.Lock()

    # -- wiring -------------------------------------------------------------

    def attach(self, tracer=None, registry=None, reqtrace=None,
               state_fn: Optional[Callable[[], dict]] = None
               ) -> "FlightRecorder":
        """Attach obs sources piecemeal (any subset; later calls only
        overwrite what they pass)."""
        if tracer is not None:
            self._tracer = tracer
        if registry is not None:
            self._registry = registry
        if reqtrace is not None:
            self._reqtrace = reqtrace
        if state_fn is not None:
            self._state_fn = state_fn
        return self

    def attach_engine(self, engine, name: Optional[str] = None
                      ) -> "FlightRecorder":
        """Wire an LLMEngine: its tracer, metrics registry, request
        registry, and `state_digest` become the dump sources, and
        `engine.flight = self` arms the engine's own death trigger."""
        if name is not None:
            self.name = str(name)
        self.attach(tracer=getattr(engine, "tracer", None),
                    registry=getattr(engine, "metrics", None),
                    reqtrace=getattr(engine, "reqtrace", None),
                    state_fn=getattr(engine, "state_digest", None))
        engine.flight = self
        return self

    # -- snapshot / dump ----------------------------------------------------

    def snapshot(self, reason: str, error: Optional[BaseException] = None,
                 extra: Optional[dict] = None) -> dict:
        """One black-box frame: recent spans, metrics text + counter
        values, the engine state digest, recent request timelines.
        `extra` is a caller-supplied JSON-safe section (the anomaly
        watchdog attaches its phase deltas here).  Every source is read
        best-effort — a half-dead engine must not turn its own
        post-mortem into a second crash."""
        snap = {
            "schema": SCHEMA,
            "reason": str(reason),
            "name": self.name,
            "wall_time": time.time(),
            "perf_time": time.perf_counter(),
            "error": None if error is None else repr(error),
            "extra": extra,
            "spans": [],
            "metrics": None,
            "engine": None,
            "requests": None,
        }
        try:
            if self._tracer is not None:
                evs = self._tracer.events()[-self.max_spans:]
                snap["spans"] = [
                    {"name": e.name, "t0": e.t0, "t1": e.t1, "ph": e.ph,
                     "step": e.step,
                     **({"attrs": dict(e.attrs)} if e.attrs else {})}
                    for e in evs]
        except Exception:  # noqa: BLE001 — best-effort post-mortem
            pass
        try:
            if self._registry is not None:
                snap["metrics"] = self._registry.render()
        except Exception:  # noqa: BLE001
            pass
        try:
            if self._state_fn is not None:
                snap["engine"] = self._state_fn()
        except Exception:  # noqa: BLE001
            pass
        try:
            if self._reqtrace is not None:
                snap["requests"] = self._reqtrace.snapshot(
                    limit=self.max_requests)
        except Exception:  # noqa: BLE001
            pass
        return snap

    def dump(self, reason: str, error: Optional[BaseException] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Snapshot and (when `dir` is set) write atomically.  Returns
        the path written, or None in in-memory mode.  NEVER raises —
        this runs inside dying threads and signal handlers."""
        try:
            snap = self.snapshot(reason, error, extra=extra)
        except Exception:  # noqa: BLE001 — even snapshot() failing must
            return None    # not escalate the crash being recorded
        self.last = snap
        if self.dir is None:
            return None
        try:
            os.makedirs(self.dir, exist_ok=True)
            with FlightRecorder._seq_lock:
                FlightRecorder._seq += 1
                seq = FlightRecorder._seq
            fname = (f"flight_{self.name}_{os.getpid()}_{seq:04d}"
                     f"_{reason}.json")
            path = os.path.join(self.dir, fname)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, path)       # atomic: never a torn dump
            with self._lock:
                self.dumps.append(path)
            return path
        except Exception:  # noqa: BLE001
            return None


def load_dump(path: str) -> dict:
    """Read a flight dump back, validating the schema — the assertion
    surface the chaos tools use ("this crash left a loadable black
    box").  Raises ValueError on a foreign/truncated file."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("schema") != SCHEMA:
        raise ValueError(
            f"{path!r} is not a flight dump (schema="
            f"{data.get('schema') if isinstance(data, dict) else None!r}, "
            f"want {SCHEMA!r})")
    missing = [k for k in _REQUIRED if k not in data]
    if missing:
        raise ValueError(f"flight dump {path!r} missing keys: {missing}")
    return data


def install_sigterm(recorders, chain: bool = True):
    """Arm SIGTERM: dump every recorder, then run (or restore) the
    previous disposition.  Opt-in, main-thread only — tools call this;
    library code never touches process signal state.  `recorders` is
    read LIVE at fire time (a sequence the caller may keep appending to
    as schedules build engines).  Returns the handler installed (tests
    invoke it directly)."""
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        # the handler interrupts the MAIN thread mid-bytecode — it may
        # already hold a registry/tracer lock snapshot() needs, and a
        # plain dump() here would deadlock against our own frame.  Dump
        # from a helper thread with a bounded join instead: the worst
        # case (signal landed inside a locked region) degrades to a
        # partial dump after the timeout, never a hung termination.
        def _dump_all():
            for r in list(recorders):
                r.dump("sigterm")

        t = threading.Thread(target=_dump_all, daemon=True)
        t.start()
        t.join(timeout=10.0)
        if chain and callable(prev):
            prev(signum, frame)
        elif chain and prev == signal.SIG_DFL:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    signal.signal(signal.SIGTERM, _handler)
    return _handler
