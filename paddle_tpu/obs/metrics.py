"""Metrics registry: counters, gauges, fixed-bucket histograms.

Prometheus-shaped (`render()` emits text exposition format 0.0.4, served
by `serve_llm`'s `GET /metrics`) but deliberately small: no label
cardinality explosion, no timestamps, no client library.  Conventions:

  * Counters are cumulative; `set()` exists so the engine's legacy
    `stats[...] = n` writes can be backed by the registry (the /stats
    JSON and /metrics text then read the SAME storage and cannot drift).
  * Histograms use fixed bucket edges with Prometheus `le` semantics
    (inclusive upper bound, cumulative counts, +Inf implicit).  They
    also keep a bounded ring of RAW samples (`samples()`), because
    percentiles interpolated from coarse buckets are too blunt for the
    TTFT/ITL numbers bench.py reports — the ring gives exact p50/p99
    over the recent window.
  * Gauges may wrap a callable (`Gauge.set_function`) so render-time
    reads instantaneous engine state (queue depth, free pages) without
    the engine pushing on every step.
"""

from __future__ import annotations

import bisect
import collections
import math
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry",
           "DEFAULT_LATENCY_BUCKETS", "percentile", "render_merged"]

# seconds; spans queue-wait through long decode tails
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _fmt(v: float) -> str:
    f = float(v)
    # non-finite first: int(nan/-inf) raises, and a dead gauge rendering
    # NaN must not take the whole /metrics scrape down with it
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f == int(f) else repr(f)


def _escape_label_value(v) -> str:
    """Prometheus text-format label escaping: backslash, double-quote,
    and newline must be escaped or a replica named `a"b` corrupts every
    sample line it labels (scrapers reject the whole exposition)."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _merge_labels(own: Optional[dict],
                  extra: Optional[dict]) -> Optional[dict]:
    if not extra:
        return own
    return {**(own or {}), **extra}


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        self._lock = threading.Lock()

    def sample_lines(self, extra_labels: Optional[dict] = None
                     ) -> List[str]:  # pragma: no cover — abstract
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def set(self, v: float) -> None:
        """Absolute write — for registry-backed legacy counter dicts."""
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def sample_lines(self, extra_labels: Optional[dict] = None) -> List[str]:
        labels = _merge_labels(self.labels, extra_labels)
        return [f"{self.name}{_fmt_labels(labels)} {_fmt(self._value)}"]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def set_function(self, fn: Callable[[], float]) -> "Gauge":
        """Read the gauge from `fn()` at render/value time (instantaneous
        engine state without push-on-every-step)."""
        self._fn = fn
        return self

    def _read(self) -> float:
        """The raw read — PROPAGATES a callback's exception.  The render
        layer catches it, skips this metric, and counts the error; the
        `value` property degrades it to NaN for in-process readers."""
        if self._fn is not None:
            return float(self._fn())
        return self._value

    @property
    def value(self) -> float:
        try:
            return self._read()
        except Exception:  # noqa: BLE001 — a dying engine must not
            return float("nan")  # crash a router's score read

    def sample_lines(self, extra_labels: Optional[dict] = None) -> List[str]:
        labels = _merge_labels(self.labels, extra_labels)
        return [f"{self.name}{_fmt_labels(labels)} {_fmt(self._read())}"]


class Histogram(_Metric):
    """Fixed-bucket histogram with Prometheus `le` semantics plus a
    bounded raw-sample ring for exact recent percentiles."""

    kind = "histogram"

    def __init__(self, name, help="", buckets: Sequence[float] =
                 DEFAULT_LATENCY_BUCKETS, labels=None,
                 sample_window: int = 4096):
        super().__init__(name, help, labels)
        edges = sorted(float(b) for b in buckets)
        if not edges:
            raise ValueError("histogram needs at least one bucket edge")
        self.edges: Tuple[float, ...] = tuple(edges)
        self._counts = [0] * (len(edges) + 1)   # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._samples: collections.deque = collections.deque(
            maxlen=sample_window)

    def observe(self, v: float) -> None:
        v = float(v)
        # le is an INCLUSIVE upper bound: v == edge lands in that bucket
        i = bisect.bisect_left(self.edges, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._samples.append(v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _cumulative(self, counts: List[int]) -> Dict[float, int]:
        out, cum = {}, 0
        for edge, c in zip(self.edges, counts):
            cum += c
            out[edge] = cum
        out[math.inf] = cum + counts[-1]
        return out

    def bucket_counts(self) -> Dict[float, int]:
        """Cumulative counts per `le` edge (+Inf included) — the exact
        numbers the text format exposes."""
        with self._lock:
            counts = list(self._counts)
        return self._cumulative(counts)

    def samples(self) -> List[float]:
        with self._lock:
            return list(self._samples)

    def percentile(self, q: float) -> float:
        """Exact percentile over the recent raw-sample window (NOT a
        bucket interpolation)."""
        return percentile(self.samples(), q)

    def sample_lines(self, extra_labels: Optional[dict] = None) -> List[str]:
        # ONE snapshot under the lock: a concurrent observe() must not
        # let the exposed _count disagree with the +Inf bucket (the
        # Prometheus histogram invariant scrapers rely on)
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        lines = []
        labels = _merge_labels(self.labels, extra_labels)
        base = dict(labels) if labels else {}
        for edge, cum in self._cumulative(counts).items():
            lines.append(f"{self.name}_bucket"
                         f"{_fmt_labels({**base, 'le': _fmt(edge)})} {cum}")
        lines.append(f"{self.name}_sum{_fmt_labels(labels)} "
                     f"{_fmt(total_sum)}")
        lines.append(f"{self.name}_count{_fmt_labels(labels)} "
                     f"{total_count}")
        return lines


def percentile(values: Iterable[float], q: float) -> float:
    vals = sorted(values)
    if not vals:
        return 0.0
    k = (len(vals) - 1) * float(q)
    lo = int(k)
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (k - lo)


_RENDER_ERRORS_NAME = "obs_render_errors_total"
_RENDER_ERRORS_HELP = ("metrics skipped from a render because their "
                       "read/callback raised (the scrape survived)")


def render_merged(registries, label: str = "replica",
                  extra_error_counts: Optional[dict] = None) -> str:
    """One Prometheus text blob over SEVERAL registries: every sample line
    from registry `name` gains a `{label="name"}` label, and families
    sharing a metric name across registries emit HELP/TYPE exactly once.

    This is how a fleet router exposes N per-replica engine registries on
    a single `GET /metrics` without pooling their storage (each engine
    keeps exclusive ownership of its counters — aggregation happens at
    render time, never at write time).  `registries` is a dict (or
    (name, Registry) iterable); names become label values, so keep them
    low-cardinality (replica ids, not request ids).

    A metric whose read raises (a gauge callback into a dying engine) is
    SKIPPED, not fatal: the rest of the fleet still renders, and the
    owning registry's `obs_render_errors_total` counts the skip — one
    bad callback must never take down the whole fleet scrape.
    `extra_error_counts` ({name: count}) adds labeled samples to that
    family for registries rendered OUTSIDE this call (the fleet handler
    concatenates the router's own `render(errors_family=False)` in
    front, so the family is declared exactly once per scrape — a second
    TYPE line for the same name makes parsers reject the exposition)."""
    items = registries.items() if hasattr(registries, "items") \
        else list(registries)
    families: "collections.OrderedDict[str, list]" = \
        collections.OrderedDict()
    err_lines = []
    for rname, reg in items:
        extra = {label: rname}
        for m in reg.collect():
            try:
                samples = m.sample_lines(extra_labels=extra)
            except Exception:  # noqa: BLE001 — skip, count, render on
                reg._note_render_error()
                continue
            fam = families.get(m.name)
            if fam is None:
                fam = families[m.name] = [m.help, m.kind, []]
            fam[2].extend(samples)
        err_lines.append(
            f"{_RENDER_ERRORS_NAME}{_fmt_labels(extra)} "
            f"{reg.render_errors_total}")
    for name, count in (extra_error_counts or {}).items():
        err_lines.append(
            f"{_RENDER_ERRORS_NAME}{_fmt_labels({label: name})} "
            f"{int(count)}")
    lines = []
    for name, (help_text, kind, samples) in families.items():
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
    lines.append(f"# HELP {_RENDER_ERRORS_NAME} {_RENDER_ERRORS_HELP}")
    lines.append(f"# TYPE {_RENDER_ERRORS_NAME} counter")
    lines.extend(err_lines)
    return "\n".join(lines) + "\n"


class Registry:
    """Named metric store; one per engine (or per process for training).
    Metric families share a name; labeled children are distinguished by
    their label dict."""

    def __init__(self):
        self._metrics: "collections.OrderedDict[tuple, _Metric]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._render_errors = 0

    def _note_render_error(self) -> None:
        with self._lock:
            self._render_errors += 1

    @property
    def render_errors_total(self) -> int:
        """Metrics skipped from render() / render_merged() because their
        read raised — rendered as `obs_render_errors_total`."""
        return self._render_errors

    def _get_or_make(self, cls, name, help, labels, **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, labels=labels, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{m.kind}")
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get_or_make(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get_or_make(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  labels: Optional[dict] = None,
                  sample_window: int = 4096) -> Histogram:
        return self._get_or_make(Histogram, name, help, labels,
                                 buckets=buckets,
                                 sample_window=sample_window)

    def get(self, name: str, labels: Optional[dict] = None):
        key = (name, tuple(sorted((labels or {}).items())))
        return self._metrics.get(key)

    def collect(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def render(self, errors_family: bool = True) -> str:
        """Prometheus text exposition format 0.0.4.  Families sharing a
        name emit HELP/TYPE once, then every child's samples.  A metric
        whose read raises (a gauge callback into torn-down state) is
        SKIPPED and counted in `obs_render_errors_total` — the scrape
        always returns the rest.  errors_family=False omits that
        family's block (callers concatenating this render with
        `render_merged` pass the count through `extra_error_counts`
        instead, so the family is declared once per scrape)."""
        by_family: "collections.OrderedDict[str, List[_Metric]]" = \
            collections.OrderedDict()
        for m in self.collect():
            by_family.setdefault(m.name, []).append(m)
        lines = []
        for name, family in by_family.items():
            samples = []
            for m in family:
                try:
                    samples.extend(m.sample_lines())
                except Exception:  # noqa: BLE001 — skip, count, go on
                    self._note_render_error()
            if not samples:
                continue
            head = family[0]
            if head.help:
                lines.append(f"# HELP {name} {head.help}")
            lines.append(f"# TYPE {name} {head.kind}")
            lines.extend(samples)
        if errors_family:
            lines.append(f"# HELP {_RENDER_ERRORS_NAME} "
                         f"{_RENDER_ERRORS_HELP}")
            lines.append(f"# TYPE {_RENDER_ERRORS_NAME} counter")
            lines.append(f"{_RENDER_ERRORS_NAME} {self._render_errors}")
        return "\n".join(lines) + "\n"
