"""Per-request trace context: bounded event rings keyed by request id.

The span tracer (obs.trace) answers "where does a STEP spend its time";
it cannot answer "where did REQUEST 7f3a spend its time", because a
request's life crosses threads (HTTP handler -> engine step thread),
components (router -> engine), and — in a fleet — replicas (placed on
replica 0, replica 0 dies, retried on replica 1).  This module is that
second axis: every lifecycle edge of a request appends one `ReqEvent`
to the request's own bounded ring, and the registry holds the rings for
the most recent requests.

Design constraints mirror the tracer's:

  1. Disabled cost ~ zero: `RequestRegistry.event()` is ONE branch when
     disabled.  Enabled cost is one lock + two dict/deque ops — small
     enough to leave on in soak runs (bench.py `extra.obs_overhead`
     pins the full-engine overhead under 2% of decode ITL).
  2. Bounded memory twice over: each timeline is a
     `deque(maxlen=events_per_request)` (a 10k-token decode keeps its
     most recent edges, not all of them — `dropped` counts the rest),
     and the registry itself is an LRU of `max_requests` timelines.
  3. One registry per FLEET, not per engine: the router and every
     replica engine default to the shared process registry
     (`get_request_registry()`), so a request's hop from a dead replica
     to its successor lands in ONE timeline.  `replica` on each event
     says who wrote it.

Timestamps are `time.perf_counter()` — the same clock the span tracer
uses, so `trace.export_merged` can place request events on the replica
tracks and stitch hops with Perfetto flow arrows.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid
from typing import Dict, List, Optional

__all__ = ["ReqEvent", "RequestTimeline", "RequestRegistry",
           "get_request_registry", "set_request_registry",
           "new_request_id"]


def new_request_id() -> str:
    """A fresh request id: 16 hex chars, unique enough for a fleet's
    LRU window.  Callers (HTTP ingress) may supply their own instead —
    any non-empty string keys a timeline."""
    return uuid.uuid4().hex[:16]


class ReqEvent:
    """One lifecycle edge of one request.  `t` is perf_counter seconds
    (the span tracer's clock); `replica` is the writing component's name
    (a replica id, or "router"); `hop` is the request's engine-level
    placement count at the time (0 = first placement)."""

    __slots__ = ("name", "t", "replica", "hop", "attrs")

    def __init__(self, name: str, t: float, replica: Optional[str],
                 hop: Optional[int], attrs: Optional[dict]):
        self.name = name
        self.t = t
        self.replica = replica
        self.hop = hop
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {"name": self.name, "t": self.t}
        if self.replica is not None:
            d["replica"] = self.replica
        if self.hop is not None:
            d["hop"] = self.hop
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d

    def __repr__(self):
        return (f"ReqEvent({self.name!r}, replica={self.replica!r}, "
                f"hop={self.hop})")


class RequestTimeline:
    """One request's bounded event ring."""

    __slots__ = ("req_id", "events", "dropped", "t_first")

    def __init__(self, req_id: str, maxlen: int):
        self.req_id = req_id
        self.events: collections.deque = collections.deque(maxlen=maxlen)
        self.dropped = 0        # events the ring overwrote
        self.t_first: Optional[float] = None

    def append(self, ev: ReqEvent) -> None:
        if self.t_first is None:
            self.t_first = ev.t
        if len(self.events) == self.events.maxlen:
            self.dropped += 1
        self.events.append(ev)

    @property
    def replicas(self) -> List[str]:
        """Distinct replica names in first-touch order — the request's
        journey across the fleet."""
        seen: List[str] = []
        for e in self.events:
            if e.replica is not None and e.replica not in seen:
                seen.append(e.replica)
        return seen

    def to_dict(self) -> dict:
        evs = list(self.events)
        return {
            "request_id": self.req_id,
            "events": [e.to_dict() for e in evs],
            "dropped": self.dropped,
            "replicas": self.replicas,
            "duration_s": (evs[-1].t - self.t_first
                           if evs and self.t_first is not None else 0.0),
        }


class RequestRegistry:
    """LRU map request id -> RequestTimeline; the queryable store behind
    `GET /debug/request/<id>` and the flight recorder's request section.

    Thread-safe: HTTP handler threads, engine step threads, and the
    router health tick all write concurrently.  `event()` is one branch
    while disabled."""

    def __init__(self, max_requests: int = 1024,
                 events_per_request: int = 256, enabled: bool = True):
        self.enabled = bool(enabled)
        self.max_requests = int(max_requests)
        self.events_per_request = int(events_per_request)
        self._timelines: "collections.OrderedDict[str, RequestTimeline]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    # -- control ------------------------------------------------------------

    def enable(self) -> "RequestRegistry":
        self.enabled = True
        return self

    def disable(self) -> "RequestRegistry":
        self.enabled = False
        return self

    def clear(self) -> None:
        with self._lock:
            self._timelines.clear()

    # -- recording ----------------------------------------------------------

    def event(self, req_id: Optional[str], name: str,
              replica: Optional[str] = None, hop: Optional[int] = None,
              **attrs) -> None:
        """Append one lifecycle edge to `req_id`'s ring.  No-op when
        disabled or req_id is falsy (an untraced request costs one
        branch, never an allocation)."""
        if not self.enabled or not req_id:
            return
        ev = ReqEvent(name, time.perf_counter(), replica, hop,
                      attrs or None)
        with self._lock:
            tl = self._timelines.get(req_id)
            if tl is None:
                tl = self._timelines[req_id] = RequestTimeline(
                    req_id, self.events_per_request)
                while len(self._timelines) > self.max_requests:
                    self._timelines.popitem(last=False)   # LRU eviction
            else:
                self._timelines.move_to_end(req_id)
            tl.append(ev)

    # -- reading ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._timelines)

    def ids(self) -> List[str]:
        """Request ids, oldest-touched first."""
        with self._lock:
            return list(self._timelines)

    def timeline(self, req_id: str) -> Optional[RequestTimeline]:
        with self._lock:
            return self._timelines.get(req_id)

    def to_dict(self, req_id: str) -> Optional[dict]:
        """The `GET /debug/request/<id>` payload (None when unknown —
        evicted, or never traced).  Converted UNDER the registry lock:
        a live timeline's ring is appended to by step threads, and
        iterating it outside the lock is a deque-mutated-during-
        iteration crash on a busy engine (threadlint: the reqtrace
        ring-append vs /debug-read race)."""
        with self._lock:
            tl = self._timelines.get(req_id)
            return None if tl is None else tl.to_dict()

    def snapshot(self, limit: Optional[int] = 32) -> List[dict]:
        """The most recently touched `limit` timelines as dicts — the
        flight recorder's request section."""
        with self._lock:
            ids = list(self._timelines)
            if limit is not None:
                ids = ids[-int(limit):]
            out = []
            for rid in ids:
                tl = self._timelines.get(rid)
                if tl is not None:
                    out.append(tl.to_dict())
            return out


# one registry per FLEET by default: router + all replica engines write
# here unless handed their own, so a retried request's hops share a ring
_default = RequestRegistry()


def get_request_registry() -> RequestRegistry:
    return _default


def set_request_registry(registry: RequestRegistry) -> RequestRegistry:
    """Swap the process default (tests isolate themselves with this).
    Returns the previous registry."""
    global _default
    prev, _default = _default, registry
    return prev
