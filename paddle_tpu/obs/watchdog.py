"""Anomaly watchdog: rolling-baseline spike detection with phase blame.

The SLO engine (obs.slo) answers "is the p95 where we promised"; the
step profiler (obs.stepprof) answers "where does a step spend time".
The watchdog closes the loop between them: it watches step time and
inter-token latency against their own ROLLING BASELINE, and when a
spike SUSTAINS, it attributes the regression to the phase(s) whose
time grew and drops a `step_anomaly` black-box dump through the
existing flight-recorder seam — so a 3am latency cliff leaves behind
not just "steps got slow" but "steps got slow because `dispatch` went
from 2.1ms to 19.8ms while everything else held".

Baseline math (documented here because the dump carries its inputs):

  * per metric ("step", "itl") keep a ring of the last
    `baseline_window + recent_window` samples; the OLD part is the
    baseline, the newest `recent_window` are the probe.
  * spike condition: `median(recent) > threshold * median(baseline)`,
    evaluated only once the baseline holds >= `min_baseline` samples
    (medians, not means: one GC pause in either window must not arm
    or mask the detector).
  * a spike must hold for `sustain` consecutive evaluations before
    firing — transient jitter never dumps.
  * attribution: per phase, `delta = median(recent self time) -
    median(baseline self time)` over the step-phase ring; phases are
    ranked by delta and the guilty set is every phase carrying >= 25%
    of the total positive delta (at least the top one).
  * after firing, the detector holds off for `cooldown` observations
    (the spike that fired would otherwise re-fire every step while it
    drains into the baseline).

The dump rides `FlightRecorder.dump("step_anomaly", extra=...)`: the
standard black-box frame (spans, metrics render, request timelines,
engine digest) plus an `extra` section carrying the metric, the
baseline/recent medians, and the per-phase deltas with the guilty
list.  Without an armed recorder the watchdog still counts
(`llm_step_anomalies_total`) and marks the tracer ("step_anomaly"
instant), so /metrics shows anomalies even on engines that never
configured a dump directory.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

from . import metrics as obs_metrics

__all__ = ["Watchdog"]


def _median(vals: List[float]) -> float:
    return obs_metrics.percentile(vals, 0.5)


class _Track:
    """One watched metric's rings + sustain/cooldown state."""

    __slots__ = ("samples", "phases", "sustained", "cooldown_left",
                 "baseline_med", "stale")

    def __init__(self, capacity: int, keep_phases: bool):
        self.samples: collections.deque = collections.deque(
            maxlen=capacity)
        # parallel ring of per-step phase dicts (step track only)
        self.phases: Optional[collections.deque] = (
            collections.deque(maxlen=capacity) if keep_phases else None)
        self.sustained = 0
        self.cooldown_left = 0
        # baseline median cache: the baseline shifts by ONE sample per
        # observation, so its median is recomputed lazily every
        # recent_window appends instead of sorting the whole ring per
        # step (the hot-loop cost is then one 8-sample median)
        self.baseline_med: Optional[float] = None
        self.stale = 0


class Watchdog:
    """Rolling-baseline anomaly detector over step time and ITL.

    The engine feeds it from the step loop: `observe_step(total_s,
    phases, flight=...)` once per step (evaluates both tracks) and
    `observe_itl(gap_s)` per inter-token gap (records only — ITL
    spikes are evaluated at the next step boundary, where the flight
    recorder reference is in hand).  Thread-safety: records take the
    lock; evaluation runs on the step thread only."""

    def __init__(self, baseline_window: int = 128,
                 recent_window: int = 8, threshold: float = 3.0,
                 min_baseline: int = 32, sustain: int = 3,
                 cooldown: Optional[int] = None, enabled: bool = True):
        if recent_window < 1 or baseline_window < 1:
            raise ValueError("windows must be >= 1")
        if threshold <= 1.0:
            raise ValueError("threshold must be > 1 (a spike is a "
                             "multiple of the baseline)")
        self.enabled = bool(enabled)
        self.baseline_window = int(baseline_window)
        self.recent_window = int(recent_window)
        self.threshold = float(threshold)
        self.min_baseline = int(min_baseline)
        self.sustain = int(sustain)
        self.cooldown = (2 * self.recent_window if cooldown is None
                         else int(cooldown))
        cap = self.baseline_window + self.recent_window
        self._tracks: Dict[str, _Track] = {
            "step": _Track(cap, keep_phases=True),
            "itl": _Track(cap, keep_phases=False),
        }
        self._lock = threading.Lock()
        self.anomalies_total = 0
        self.last_anomaly: Optional[dict] = None
        self._tracer = None
        self._counter = None

    def bind(self, tracer=None, registry=None) -> "Watchdog":
        """Attach the obs surfaces the watchdog marks on fire: a tracer
        (one "step_anomaly" instant per fire) and a metrics registry
        (`llm_step_anomalies_total` counter + `llm_watchdog_armed`
        gauge)."""
        if tracer is not None:
            self._tracer = tracer
        if registry is not None:
            self._counter = registry.counter(
                "llm_step_anomalies_total",
                "sustained step-time/ITL spikes the watchdog attributed "
                "and dumped")
            registry.gauge(
                "llm_watchdog_armed",
                "1 while the anomaly watchdog has a full enough "
                "baseline to fire").set_function(
                lambda: float(self.armed()))
        return self

    # -- feeding ------------------------------------------------------------

    def observe_itl(self, gap_s: float) -> None:
        """Record one inter-token gap.  Evaluation happens at the next
        observe_step (the step boundary owns the flight reference)."""
        if not self.enabled:
            return
        with self._lock:
            t = self._tracks["itl"]
            t.samples.append(float(gap_s))
            t.stale += 1

    def observe_step(self, total_s: float,
                     phases: Optional[Dict[str, float]] = None,
                     flight=None) -> Optional[dict]:
        """Record one step and evaluate both tracks.  Returns the
        anomaly dict when one fired this call (tests read it), else
        None."""
        if not self.enabled:
            return None
        tr = self._tracks["step"]
        with self._lock:
            tr.samples.append(float(total_s))
            tr.phases.append(dict(phases or {}))
            tr.stale += 1
        fired = self._evaluate("step", flight)
        if fired is None:
            fired = self._evaluate("itl", flight)
        return fired

    # -- detection ----------------------------------------------------------

    def _split(self, track: _Track):
        samples = list(track.samples)
        if len(samples) < self.min_baseline + self.recent_window:
            return None, None
        return (samples[:-self.recent_window],
                samples[-self.recent_window:])

    def armed(self, metric: str = "step") -> bool:
        track = self._tracks[metric]
        with self._lock:
            baseline, _ = self._split(track)
        return baseline is not None

    def _evaluate(self, metric: str, flight) -> Optional[dict]:
        track = self._tracks[metric]
        with self._lock:
            if track.cooldown_left > 0:
                track.cooldown_left -= 1
                return None
            baseline, recent = self._split(track)
            if baseline is None:
                return None
            if track.baseline_med is None \
                    or track.stale >= self.recent_window:
                track.baseline_med = _median(baseline)
                track.stale = 0
            base_med = track.baseline_med
            rec_med = _median(recent)
            spiking = (base_med > 0.0
                       and rec_med > self.threshold * base_med)
            if not spiking:
                track.sustained = 0
                return None
            track.sustained += 1
            if track.sustained < self.sustain:
                return None
            # firing: reset sustain, open the cooldown window
            track.sustained = 0
            track.cooldown_left = self.cooldown
            deltas, guilty = self._attribute()
            self.anomalies_total += 1
            anomaly = {
                "metric": metric,
                "baseline_median_s": base_med,
                "recent_median_s": rec_med,
                "ratio": (rec_med / base_med) if base_med else None,
                "threshold": self.threshold,
                "baseline_n": len(baseline),
                "recent_n": len(recent),
                "phase_deltas_s": deltas,
                "guilty_phases": guilty,
            }
            self.last_anomaly = anomaly
        # side effects OUTSIDE the lock: the flight dump renders the
        # registry, whose gauges may read back into this watchdog
        if self._counter is not None:
            self._counter.inc()
        if self._tracer is not None:
            self._tracer.instant("step_anomaly", metric=metric,
                                 ratio=anomaly["ratio"],
                                 guilty=",".join(guilty))
        if flight is not None:
            try:
                flight.dump("step_anomaly", extra=anomaly)
            except Exception:  # noqa: BLE001 — a recorder bug must not
                pass           # fail the step loop
        return anomaly

    def _attribute(self) -> tuple:
        """Per-phase blame over the step-phase ring: delta of medians
        (recent - baseline) per phase; guilty = every phase carrying
        >= 25% of the total positive delta, at least the top one.
        Called under the lock."""
        track = self._tracks["step"]
        frames = list(track.phases)
        if len(frames) < self.min_baseline + self.recent_window:
            return {}, []
        base_frames = frames[:-self.recent_window]
        rec_frames = frames[-self.recent_window:]
        names = set()
        for f in base_frames + rec_frames:
            names.update(f)
        deltas: Dict[str, float] = {}
        for name in names:
            base = _median([f.get(name, 0.0) for f in base_frames])
            rec = _median([f.get(name, 0.0) for f in rec_frames])
            deltas[name] = rec - base
        positive = sum(d for d in deltas.values() if d > 0.0)
        ranked = sorted(deltas.items(), key=lambda kv: -kv[1])
        guilty = [name for name, d in ranked
                  if d > 0.0 and positive > 0.0 and d >= 0.25 * positive]
        if not guilty and ranked and ranked[0][1] > 0.0:
            guilty = [ranked[0][0]]
        return deltas, guilty

    # -- reading ------------------------------------------------------------

    def report(self) -> dict:
        """The `/stats` watchdog section: armed state, fire count, and
        the last anomaly (None until one fires)."""
        with self._lock:
            step_n = len(self._tracks["step"].samples)
            itl_n = len(self._tracks["itl"].samples)
            # count + last-anomaly snapshot under the same lock as the
            # writer (_note_anomaly): read outside it, a fire between
            # the two reads reports total=N+1 with anomaly N-1's detail
            anomalies_total = self.anomalies_total
            last_anomaly = self.last_anomaly
        armed = (self.enabled
                 and step_n >= self.min_baseline + self.recent_window)
        return {
            "enabled": self.enabled,
            "armed": armed,
            "threshold": self.threshold,
            "sustain": self.sustain,
            "step_samples": step_n,
            "itl_samples": itl_n,
            "anomalies_total": anomalies_total,
            "last_anomaly": last_anomaly,
        }
