"""Span tracer: ring-buffered wall-time spans with device fencing.

Design constraints, in order:

  1. Disabled cost ~ zero.  `Tracer.span()` is ONE branch when disabled,
     returning a shared no-op context manager — no allocation, no clock
     read.  Instrumentation can therefore live permanently inside the
     engine's decode loop.
  2. Honest device timing.  jax dispatch is async: closing a span right
     after `fn(...)` times the *enqueue*.  A span carrying a fence value
     (`sp.fence(arrays)`) calls `jax.block_until_ready` on it before
     taking the closing timestamp, so the span covers the compute.  On
     CPU interpret paths execution is synchronous and the fence is a
     cheap no-op — but keep it: the same code path must time correctly
     on a real chip.
  3. Bounded memory.  Events land in a `deque(maxlen=capacity)`; a
     long-running server overwrites its oldest spans instead of growing.

Spans record (name, t0, t1, thread, step, attrs).  `step` is the
current profiler step lane — `step_mark(n)` (called by
`profiler.Profiler.step()` and the hapi ObsCallback) assigns subsequent
spans on that thread to step `n`, which the Chrome exporter renders as
per-step lanes instead of one flat track.

Export: `export_chrome(path)` writes chrome://tracing / Perfetto JSON
(`ph:"X"` complete events in microseconds); `load_trace(path)` reads it
back; `summarize(events_or_path)` aggregates per-name totals and
percentiles — the table `tools/trace_summary.py` prints.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Union

__all__ = ["SpanEvent", "Tracer", "get_tracer", "set_tracer", "load_trace",
           "summarize", "format_summary", "export_merged", "REQUEST_LANE"]

# the synthetic per-process lane merged exports place request lifecycle
# events on (one "requests" track per replica, below its thread lanes)
REQUEST_LANE = 2 ** 31 - 1


class SpanEvent:
    """One recorded span (ph="X") or instant (ph="i"); times are
    `time.perf_counter()` seconds."""

    __slots__ = ("name", "t0", "t1", "tid", "step", "attrs", "ph")

    def __init__(self, name, t0, t1, tid, step=None, attrs=None, ph="X"):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.step = step
        self.attrs = attrs
        self.ph = ph

    @property
    def dur(self) -> float:
        return self.t1 - self.t0

    def __repr__(self):
        return (f"SpanEvent({self.name!r}, dur={self.dur * 1e3:.3f}ms, "
                f"step={self.step})")


class _NoopSpan:
    """Shared do-nothing span returned while the tracer is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, value):
        return self

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "attrs", "_t0", "_fence")

    def __init__(self, tracer: "Tracer", name: str, attrs: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._fence = None

    def fence(self, value) -> "_Span":
        """Block on `value` (any pytree of jax arrays) before the closing
        timestamp, so the span covers the device compute, not the
        enqueue."""
        self._fence = value
        return self

    def set(self, **attrs) -> "_Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._fence is not None:
            try:
                import jax

                jax.block_until_ready(self._fence)
            except Exception:  # noqa: BLE001 — a deleted/donated buffer
                pass           # must not turn a trace span into a crash
        self._tracer._record_span(self.name, self._t0, time.perf_counter(),
                                  self.attrs)
        return False


class Tracer:
    """Ring-buffered span recorder.  Disabled by default: `span()` /
    `instant()` cost one branch until `enable()` is called."""

    def __init__(self, capacity: int = 65536, enabled: bool = False):
        self.enabled = bool(enabled)
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        # step lanes are PER-THREAD: the training thread's step_mark must
        # not pull the engine thread's spans into its lane
        self._steps = threading.local()

    @property
    def _step(self) -> Optional[int]:
        return getattr(self._steps, "v", None)

    # -- control ------------------------------------------------------------

    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
        self._steps = threading.local()   # stale lanes die with the ring

    # -- recording ----------------------------------------------------------

    def span(self, name: str, **attrs):
        """Context manager timing a host span.  `with tr.span("prefill",
        slot=3) as sp: ... sp.fence(logits)`.  No-op when disabled."""
        if not self.enabled:
            return _NOOP
        return _Span(self, name, attrs or None)

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker event (warnings, recompiles, preemptions)."""
        if not self.enabled:
            return
        t = time.perf_counter()
        ev = SpanEvent(name, t, t, threading.get_ident(), self._step,
                       attrs or None, ph="i")
        with self._lock:
            self._events.append(ev)

    def counter(self, name: str, value) -> None:
        """One sample on a Perfetto COUNTER track (`ph:"C"`).  `value`
        is a number (series "value") or a {series: number} dict (the
        viewer stacks multi-series counters on one track).  The engine
        samples its pool/queue/batch gauges here every step, so a trace
        shows free-pages collapsing UNDER the span that caused it —
        counters and spans share the timeline.  No-op when disabled."""
        if not self.enabled:
            return
        series = (value if isinstance(value, dict) else {"value": value})
        series = {str(k): float(v) for k, v in series.items()}
        t = time.perf_counter()
        ev = SpanEvent(name, t, t, threading.get_ident(), self._step,
                       series, ph="C")
        with self._lock:
            self._events.append(ev)

    def record(self, name: str, t0: float, t1: float,
               attrs: Optional[dict] = None) -> None:
        """Record an externally-timed span (profiler RecordEvent feeds
        this).  No-op when disabled."""
        if not self.enabled:
            return
        self._record_span(name, t0, t1, attrs)

    def _record_span(self, name, t0, t1, attrs) -> None:
        ev = SpanEvent(name, t0, t1, threading.get_ident(), self._step,
                       attrs)
        with self._lock:
            self._events.append(ev)

    def step_mark(self, step: int) -> None:
        """Open step lane `step` ON THIS THREAD: its subsequent spans
        carry it, and the Chrome exporter groups them into per-step
        tracks.  Other threads' spans keep their thread lanes."""
        if not self.enabled:
            return
        self._steps.v = int(step)
        self.instant(f"ProfileStep#{step}", step=int(step))

    # -- reading ------------------------------------------------------------

    def events(self) -> List[SpanEvent]:
        with self._lock:
            return list(self._events)

    def export_chrome(self, path: Optional[str] = None,
                      extra: Optional[dict] = None) -> Union[str, dict]:
        """Chrome/Perfetto trace JSON.  Spans recorded inside a step lane
        get `tid = step` (with thread_name metadata "step N") so the
        viewer shows one lane per profiler step; un-stepped spans keep
        their real thread id.  Returns the path (when given) or the
        trace dict."""
        trace = {"traceEvents": _chrome_events(self.events(), os.getpid())}
        if extra:
            trace.update(extra)
        if path is None:
            return trace
        with open(path, "w") as f:
            json.dump(trace, f)
        return path


def _chrome_events(span_events, pid: int) -> List[dict]:
    """Chrome trace events (plus thread_name metadata) for one tracer's
    SpanEvents under process `pid` — shared by the single-tracer export
    and the merged fleet export."""
    events: List[dict] = []
    lanes: Dict[int, str] = {}
    for e in span_events:
        if e.step is not None:
            tid, lane = int(e.step), f"step {e.step}"
        else:
            tid, lane = int(e.tid % 2 ** 31), f"thread {e.tid}"
        lanes.setdefault(tid, lane)
        ev = {"name": e.name, "ph": e.ph,
              "cat": "counter" if e.ph == "C" else "host",
              "ts": e.t0 * 1e6, "pid": pid, "tid": tid}
        if e.ph == "X":
            ev["dur"] = (e.t1 - e.t0) * 1e6
        elif e.ph == "i":
            ev["s"] = "t"      # instant scope: thread
        # ph "C": args IS the series dict — no dur, no scope
        if e.attrs:
            ev["args"] = dict(e.attrs)
        events.append(ev)
    for tid, lane in sorted(lanes.items()):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": lane}})
    return events


def export_merged(tracers, path: Optional[str] = None, requests=None,
                  extra: Optional[dict] = None) -> Union[str, dict]:
    """ONE Perfetto trace over a fleet: every replica's tracer becomes
    its own process track (pid = registration order, process_name =
    "replica <name>"), and — when a `RequestRegistry` (or its
    `snapshot()` list) is given — each request's lifecycle events land
    on the owning replica's "requests" lane with Perfetto FLOW events
    (`ph` s/t/f sharing `id=request_id`) stitching the hops, so a
    request retried from a dead replica to its successor renders as one
    arrow across the two process tracks.

    `tracers`: {name: Tracer} dict or (name, Tracer) iterable.  Names
    must match the `replica` field request events carry (the router
    stamps engines with `replica_name=str(rid)`); events whose replica
    is unknown here (e.g. the router's own) go to a synthetic "router"
    process track.  Returns the path (when given) or the trace dict."""
    items = tracers.items() if hasattr(tracers, "items") else list(tracers)
    events: List[dict] = []
    pid_of: Dict[str, int] = {}
    for name, tr in items:
        pid = len(pid_of) + 1
        pid_of[str(name)] = pid
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"replica {name}"}})
        events.extend(_chrome_events(tr.events(), pid))

    if requests is not None:
        timelines = (requests.snapshot(limit=None)
                     if hasattr(requests, "snapshot") else list(requests))
        router_pid = None
        req_lanes = set()

        def _pid_for(replica: Optional[str]) -> int:
            nonlocal router_pid
            if replica is not None and str(replica) in pid_of:
                return pid_of[str(replica)]
            if router_pid is None:
                router_pid = len(pid_of) + 1
                events.append({"name": "process_name", "ph": "M",
                               "pid": router_pid,
                               "args": {"name": "router"}})
            return router_pid

        for tl in timelines:
            rid = tl["request_id"]
            evs = tl["events"]
            for i, ev in enumerate(evs):
                pid = _pid_for(ev.get("replica"))
                req_lanes.add(pid)
                ts = ev["t"] * 1e6
                args = {"req": rid, **ev.get("attrs", {})}
                if ev.get("hop") is not None:
                    args["hop"] = ev["hop"]
                # request events are THIN SLICES (ph "X"), not bare
                # instants: flow arrows only render when they bind to a
                # duration slice at the same pid/tid/ts — an instants-
                # only lane would silently drop every arrow in the
                # viewer.  Duration = gap to the next event on this
                # timeline, capped so a slice never paints over the
                # request's whole residency.
                if i + 1 < len(evs):
                    dur = max(1.0, min((evs[i + 1]["t"] - ev["t"]) * 1e6,
                                       1000.0))
                else:
                    dur = 1.0
                events.append({"name": ev["name"], "ph": "X",
                               "cat": "req", "ts": ts, "dur": dur,
                               "pid": pid, "tid": REQUEST_LANE,
                               "args": args})
                # flow chain: start at the first event, step through the
                # middle, finish at the last — Perfetto draws the arrows
                # that make a cross-replica hop visible as one journey
                last = i == len(evs) - 1
                ph = "s" if i == 0 else ("f" if last else "t")
                flow = {"name": "req", "ph": ph, "cat": "req", "id": rid,
                        "ts": ts, "pid": pid, "tid": REQUEST_LANE}
                if ph == "f":
                    flow["bp"] = "e"
                if len(evs) > 1:
                    events.append(flow)
        for pid in sorted(req_lanes):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": REQUEST_LANE,
                           "args": {"name": "requests"}})

    trace = {"traceEvents": events}
    if extra:
        trace.update(extra)
    if path is None:
        return trace
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


# the process-wide default tracer: the engine, the profiler, and the hapi
# callback all record here unless handed their own instance — ONE event
# spine, so a single export interleaves serving and training spans
_default = Tracer()


def get_tracer() -> Tracer:
    return _default


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process default (tests isolate themselves with this).
    Returns the previous tracer."""
    global _default
    prev, _default = _default, tracer
    return prev


def load_trace(path: str) -> List[dict]:
    """Read back an exported Chrome trace: the raw traceEvents list."""
    with open(path) as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def summarize(events_or_path) -> Dict[str, dict]:
    """Per-span-name aggregate over complete ("X") events: {name:
    {count, total_s, mean_s, p50_s, p90_s, p99_s, max_s}}.  Accepts a
    trace path, the loaded traceEvents list, or `Tracer.events()`."""
    if isinstance(events_or_path, str):
        events_or_path = load_trace(events_or_path)
    durs: Dict[str, List[float]] = {}
    for e in events_or_path:
        if isinstance(e, SpanEvent):
            if e.ph != "X":
                continue
            name, dur = e.name, e.dur
        else:
            if e.get("ph") != "X":
                continue
            name, dur = e["name"], e.get("dur", 0.0) * 1e-6
        durs.setdefault(name, []).append(dur)
    from .metrics import percentile

    out = {}
    for name, ds in durs.items():
        ds.sort()
        out[name] = {
            "count": len(ds),
            "total_s": sum(ds),
            "mean_s": sum(ds) / len(ds),
            "p50_s": percentile(ds, 0.50),
            "p90_s": percentile(ds, 0.90),
            "p99_s": percentile(ds, 0.99),
            "max_s": ds[-1],
        }
    return out


def format_summary(summary: Dict[str, dict], time_unit: str = "ms") -> str:
    """Fixed-width table of `summarize()` output, heaviest total first."""
    unit = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
    u = time_unit
    lines = [f"{'span':28}  {'count':>7}  {'total(' + u + ')':>12}  "
             f"{'mean':>10}  {'p50':>10}  {'p90':>10}  {'p99':>10}  "
             f"{'max':>10}"]
    for name, s in sorted(summary.items(), key=lambda kv: -kv[1]["total_s"]):
        lines.append(
            f"{name[:28]:28}  {s['count']:>7}  {s['total_s'] * unit:>12.3f}"
            f"  {s['mean_s'] * unit:>10.3f}  {s['p50_s'] * unit:>10.3f}"
            f"  {s['p90_s'] * unit:>10.3f}  {s['p99_s'] * unit:>10.3f}"
            f"  {s['max_s'] * unit:>10.3f}")
    return "\n".join(lines)
