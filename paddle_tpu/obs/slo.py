"""SLO engine: rolling-window latency objectives + burn-rate counters.

The latency histograms (obs.metrics) answer "what IS the p99"; an SLO
answers "is the p95 where we PROMISED, and how fast are we spending the
error budget".  This module holds the objectives and does the rolling
arithmetic:

  * an `Objective` is (metric, quantile, threshold): "p95 of TTFT stays
    under 2s".  The error budget is the quantile's complement — a p95
    objective tolerates 5% of requests over the threshold.
  * the `SLOEngine` keeps a TIMESTAMPED rolling window of samples per
    metric (the histograms' raw rings are count-bounded, not
    time-bounded — an SLO over "the last 60 seconds" needs its own
    clock), plus a cumulative violation counter per objective.
  * `burn_rate` is the SRE convention: observed error fraction in the
    window divided by the budget fraction.  1.0 = spending the budget
    exactly as fast as allowed; 10 = alarm.  0 while the window is
    empty — no traffic is not an outage.

Surfaces: `register(registry)` exposes per-objective gauges
(`slo_<metric>_p<q>_seconds`, `..._target_seconds`, `..._burn_rate`,
`..._ok`) and a violations counter in the same Prometheus registry the
engine already renders, so `/metrics` and `/stats` (via `report()`)
show objective health next to the raw histograms.  The LLMEngine
constructs one per engine and feeds it alongside the histograms, so the
observation cost is one deque append + one compare per sample.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics as obs_metrics

__all__ = ["Objective", "SLOEngine", "DEFAULT_OBJECTIVES"]


class Objective:
    """One latency objective: quantile `q` of `metric` stays under
    `threshold_s`.  `metric` names a sample stream the feeding engine
    observes ("ttft", "inter_token", "queue_wait" in the LLMEngine)."""

    def __init__(self, metric: str, q: float, threshold_s: float,
                 name: Optional[str] = None):
        # q == 1.0 is a legal (if brutal) objective: "NO sample may
        # exceed the threshold".  Its error budget is zero, so burn is
        # inf the moment one sample goes over — see report().
        if not 0.0 < float(q) <= 1.0:
            raise ValueError(f"objective quantile must be in (0, 1], "
                             f"got {q}")
        if float(threshold_s) <= 0.0:
            raise ValueError("objective threshold must be > 0")
        self.metric = str(metric)
        self.q = float(q)
        self.threshold_s = float(threshold_s)
        # "ttft_p95" — the slug metric names and report keys build on
        self.name = name or f"{self.metric}_p{round(self.q * 100)}"

    @property
    def budget(self) -> float:
        """Error budget fraction: a p95 objective tolerates 5% over."""
        return 1.0 - self.q

    def __repr__(self):
        return (f"Objective({self.metric} p{self.q * 100:g} < "
                f"{self.threshold_s}s)")


# serving defaults: generous enough that a healthy CPU-interpret test
# engine meets them, tight enough that a wedged fleet burns visibly
DEFAULT_OBJECTIVES = (
    Objective("ttft", 0.95, 2.0),
    Objective("inter_token", 0.95, 0.5),
    Objective("queue_wait", 0.95, 2.0),
)


class SLOEngine:
    """Rolling-window objective evaluation over pushed samples.

    window_s: the rolling horizon for percentiles and burn rates.
    max_samples: per-metric ring bound (memory cap under bursts).
    Thread-safe; `observe()` is cheap enough for the decode loop."""

    def __init__(self,
                 objectives: Sequence[Objective] = DEFAULT_OBJECTIVES,
                 window_s: float = 60.0, max_samples: int = 4096,
                 enabled: bool = True):
        self.objectives: Tuple[Objective, ...] = tuple(objectives)
        self.window_s = float(window_s)
        self.enabled = bool(enabled)
        self._samples: Dict[str, collections.deque] = {}
        self._violations: Dict[str, int] = {}
        self._bound: Dict[str, obs_metrics.Counter] = {}
        self._lock = threading.Lock()
        self._max_samples = int(max_samples)
        for o in self.objectives:
            self._samples.setdefault(
                o.metric, collections.deque(maxlen=self._max_samples))
            self._violations[o.name] = 0

    # -- feeding ------------------------------------------------------------

    def observe(self, metric: str, value: float,
                t: Optional[float] = None) -> None:
        """Record one sample for `metric` (seconds).  One branch while
        disabled; unknown metrics — no objective watches them — are
        dropped in one dict probe."""
        if not self.enabled:
            return
        ring = self._samples.get(metric)
        if ring is None:
            return
        v = float(value)
        if t is None:
            t = time.monotonic()
        bump: List[str] = []
        with self._lock:
            ring.append((t, v))
            for o in self.objectives:
                if o.metric == metric and v > o.threshold_s:
                    self._violations[o.name] += 1
                    bump.append(o.name)
        for name in bump:       # registry counters have their own lock
            c = self._bound.get(name)
            if c is not None:
                c.inc()

    # -- reading ------------------------------------------------------------

    def _window(self, metric: str, now: float) -> List[float]:
        ring = self._samples.get(metric)
        if not ring:
            return []
        cut = now - self.window_s
        with self._lock:
            return [v for (t, v) in ring if t >= cut]

    def report(self, now: Optional[float] = None) -> dict:
        """Per-objective verdicts over the rolling window:
        {name: {metric, quantile, target_s, window_value_s, ok,
        window_n, over_threshold_n, burn_rate, violations_total}}."""
        if now is None:
            now = time.monotonic()
        out: dict = {"window_s": self.window_s, "objectives": {}}
        for o in self.objectives:
            vals = self._window(o.metric, now)
            n = len(vals)
            value = obs_metrics.percentile(vals, o.q) if n else 0.0
            over = sum(1 for v in vals if v > o.threshold_s)
            # no traffic is not an outage: empty window reports ok with
            # zero burn instead of dividing by nothing.  A q=1.0
            # objective has ZERO budget — one violation is infinite
            # burn, not a ZeroDivisionError.
            if not n:
                burn = 0.0
            elif o.budget > 0.0:
                burn = (over / n) / o.budget
            else:
                burn = float("inf") if over else 0.0
            out["objectives"][o.name] = {
                "metric": o.metric,
                "quantile": o.q,
                "target_s": o.threshold_s,
                "window_value_s": value,
                "ok": (value <= o.threshold_s) if n else True,
                "window_n": n,
                "over_threshold_n": over,
                "burn_rate": burn,
                "violations_total": self._violations[o.name],
            }
        return out

    def register(self, registry: obs_metrics.Registry) -> "SLOEngine":
        """Expose every objective on a Prometheus registry.  Gauges read
        lazily at render time (`Gauge.set_function`), so a scrape always
        sees the current window without the engine pushing per step."""
        registry.gauge("slo_window_seconds",
                       "rolling window the SLO gauges evaluate over"
                       ).set(self.window_s)
        for o in self.objectives:
            def _value(o=o):
                vals = self._window(o.metric, time.monotonic())
                return (obs_metrics.percentile(vals, o.q)
                        if vals else 0.0)

            def _burn(o=o):
                vals = self._window(o.metric, time.monotonic())
                if not vals:
                    return 0.0
                over = sum(1 for v in vals if v > o.threshold_s)
                if o.budget <= 0.0:     # q=1.0: zero error budget
                    return float("inf") if over else 0.0
                return (over / len(vals)) / o.budget

            def _ok(o=o):
                vals = self._window(o.metric, time.monotonic())
                if not vals:
                    return 1.0
                return float(obs_metrics.percentile(vals, o.q)
                             <= o.threshold_s)

            registry.gauge(
                f"slo_{o.name}_seconds",
                f"rolling q={o.q:g} of {o.metric} (window "
                f"{self.window_s:g}s)").set_function(_value)
            registry.gauge(
                f"slo_{o.name}_target_seconds",
                f"objective: q={o.q:g} of {o.metric} stays under this"
                ).set(o.threshold_s)
            registry.gauge(
                f"slo_{o.name}_burn_rate",
                "windowed error fraction / error budget (1.0 = spending "
                "the budget exactly at the allowed rate)"
                ).set_function(_burn)
            registry.gauge(
                f"slo_{o.name}_ok",
                "1 while the windowed quantile meets the objective"
                ).set_function(_ok)
            counter = registry.counter(
                f"slo_{o.name}_violations_total",
                f"samples of {o.metric} over the {o.threshold_s:g}s "
                "objective threshold (cumulative)")
            counter.set(self._violations[o.name])
            # counters are push-model: observe() bumps the bound counter
            # so /metrics tracks violations without a lazy read
            self._bound[o.name] = counter
        return self
