"""paddle_tpu.obs — runtime telemetry: span tracing, metrics, measured MFU.

Graph Doctor (paddle_tpu.analysis) predicts what a compiled program
*should* cost — static FLOPs/bytes and liveness peaks.  This package
measures what it *actually* costs, on one shared event spine:

  * `obs.trace` — a low-overhead span tracer: `trace.span("prefill",
    req_id=...)` context managers record monotonic wall times into a ring
    buffer, with explicit `block_until_ready` fencing for device work
    (async dispatch otherwise times the *enqueue*, not the compute).
    Exportable as Chrome/Perfetto trace JSON; `profiler.Profiler` and the
    LLMEngine both record into it.
  * `obs.metrics` — counters, gauges, fixed-bucket histograms in a
    `Registry`, rendered as Prometheus text (`GET /metrics` in serve_llm).
    The engine's `/stats` JSON is sourced from the same registry, so the
    two surfaces cannot drift.
  * `obs.mfu` — closes the static/measured loop: runtime MFU from
    measured step time + the cost pass's FLOPs, `cost_model_ratio`
    (measured / predicted) per jitted target — and per PHASE via
    `phase_runtime_report` — and a `RecompileSentinel` that counts
    compile-cache misses per fn and warns when a target recompiles
    after warmup.
  * `obs.stepprof` — per-step phase attribution: disjoint self-time
    phases (schedule/build_batch/dispatch/sample/verify/commit/swap),
    rolling shares on /stats + /metrics, and a per-shape-class
    cost-model join for the dispatch (the autotuner's table).
  * `obs.watchdog` — rolling-baseline anomaly detection over step time
    and ITL; a sustained spike is attributed to the phase(s) whose
    time grew and dumped as a `step_anomaly` flight-recorder frame.

When tracing is disabled (the default) every instrumentation point is a
single attribute check returning a shared no-op span — safe to leave in
hot loops.
"""

from __future__ import annotations

from . import trace  # noqa: F401
from . import metrics  # noqa: F401
from . import mfu  # noqa: F401
from . import reqtrace  # noqa: F401
from . import flight  # noqa: F401
from . import slo  # noqa: F401
from . import stepprof  # noqa: F401
from . import watchdog  # noqa: F401
from .trace import (  # noqa: F401
    Tracer, get_tracer, load_trace, summarize, export_merged,
)
from .metrics import (  # noqa: F401
    Registry, Counter, Gauge, Histogram, render_merged,
)
from .mfu import (  # noqa: F401
    RecompileSentinel, RecompileWarning, device_peak_flops, runtime_report,
)
from .reqtrace import (  # noqa: F401
    RequestRegistry, get_request_registry, new_request_id,
)
from .flight import FlightRecorder, load_dump  # noqa: F401
from .slo import Objective, SLOEngine  # noqa: F401
from .stepprof import StepProfiler  # noqa: F401
from .watchdog import Watchdog  # noqa: F401

__all__ = [
    "trace", "metrics", "mfu", "reqtrace", "flight", "slo",
    "stepprof", "watchdog",
    "Tracer", "get_tracer", "load_trace",
    "summarize", "export_merged", "Registry", "Counter", "Gauge",
    "Histogram", "render_merged",
    "RecompileSentinel", "RecompileWarning", "device_peak_flops",
    "runtime_report",
    "RequestRegistry", "get_request_registry", "new_request_id",
    "FlightRecorder", "load_dump", "Objective", "SLOEngine",
    "StepProfiler", "Watchdog",
]
