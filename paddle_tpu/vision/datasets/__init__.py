"""vision.datasets parity (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, Flowers, VOC2012, DatasetFolder/ImageFolder).

Zero-egress environment: `download=True` raises with instructions; datasets
parse the standard on-disk formats (IDX for MNIST, pickled batches for CIFAR,
image directory trees for ImageFolder).  FakeData provides a synthetic
drop-in for tests/benchmarks."""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ...io import Dataset

__all__ = ["Flowers", "VOC2012",
           "MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "FakeData"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable in this environment "
        f"(no network egress). Place the standard files locally and pass "
        f"their paths (image_path/label_path or data_file).")


class MNIST(Dataset):
    """IDX-format MNIST (reference vision/datasets/mnist.py).

    mode: 'train' | 'test'.  Files are the standard idx3/idx1 (optionally
    .gz).  Returns (image, label); image is HWC uint8 numpy unless transform
    says otherwise."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path is None or label_path is None:
            if download:
                _no_download(type(self).NAME)
            raise ValueError("image_path and label_path are required "
                             "(no auto-download in this environment)")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        if len(self.images) != len(self.labels):
            raise ValueError("image/label count mismatch")

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if str(path).endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx3 magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols, 1)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx1 magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 from the python-pickle tar.gz (reference cifar.py)."""

    _num_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            if download:
                _no_download("cifar10")
            raise ValueError("data_file (cifar-10-python.tar.gz) required")
        self.transform = transform
        names = ([f"data_batch_{i}" for i in range(1, 6)] if mode == "train"
                 else ["test_batch"])
        if self._num_classes == 100:
            names = ["train"] if mode == "train" else ["test"]
        xs, ys = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base in names:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(np.asarray(d[b"data"], np.uint8))
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    ys.append(np.asarray(d[key], np.int64))
        if not xs:
            raise ValueError(f"no batches found in {data_file}")
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        self.labels = np.concatenate(ys)

    def __getitem__(self, idx):
        img, label = self.data[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _num_classes = 100


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


class DatasetFolder(Dataset):
    """class-per-subdirectory loader (reference vision/datasets/folder.py)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._pil_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for dirpath, _, files in sorted(os.walk(os.path.join(root, c))):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))

    @staticmethod
    def _pil_loader(path):
        from PIL import Image

        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat image-list loader (no labels) — reference folder.py ImageFolder."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.transform = transform
        self.loader = loader or DatasetFolder._pil_loader
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class FakeData(Dataset):
    """Synthetic labelled images — the test/bench stand-in for the download-
    able datasets (no reference analog needed; SURVEY.md §4 fake-device
    spirit)."""

    def __init__(self, size=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._images = self._rng.integers(
            0, 256, (size,) + self.image_shape, dtype=np.uint8)
        self._labels = self._rng.integers(0, num_classes, size).astype(np.int64)

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]

    def __len__(self):
        return self.size


class Flowers(Dataset):
    """Oxford Flowers-102 (reference vision/datasets/flowers.py).
    data_file: directory of <label>/<img>.npy or .png files (or None for
    synthetic 32x32 RGB).  Items: (image HWC uint8 | CHW float via
    transform, label int64)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None, n_synthetic=40):
        if mode not in ("train", "valid", "test"):
            raise ValueError(f"mode must be train|valid|test, got {mode}")
        self.transform = transform
        self._items = []
        if data_file is None:
            rng = np.random.default_rng(
                {"train": 102, "valid": 103, "test": 104}[mode])
            for i in range(n_synthetic):
                lab = i % 102
                base = np.full((32, 32, 3), 40 + (lab * 2) % 160, np.uint8)
                noise = rng.integers(0, 40, (32, 32, 3), dtype=np.uint8)
                self._items.append((base + noise, lab))
        else:
            import os
            for lab_name in sorted(os.listdir(data_file)):
                d = os.path.join(data_file, lab_name)
                if not os.path.isdir(d):
                    continue
                for f in sorted(os.listdir(d)):
                    p = os.path.join(d, f)
                    if f.endswith(".npy"):
                        self._items.append((np.load(p), int(lab_name)))
        self._items = [(im, np.int64(lab)) for im, lab in self._items]

    def __getitem__(self, idx):
        im, lab = self._items[idx]
        if self.transform is not None:
            im = self.transform(im)
        return im, lab

    def __len__(self):
        return len(self._items)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference vision/datasets/voc2012.py).
    data_file: a directory with JPEGImages/ + SegmentationClass/ pairs as
    .npy; None -> synthetic (image, mask) pairs.  Items: (image HWC uint8,
    mask HW int64)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, n_synthetic=20):
        if mode not in ("train", "valid", "test"):
            raise ValueError(f"mode must be train|valid|test, got {mode}")
        self.transform = transform
        self._items = []
        if data_file is None:
            rng = np.random.default_rng(
                {"train": 201, "valid": 202, "test": 203}[mode])
            for _ in range(n_synthetic):
                img = rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)
                mask = np.zeros((32, 32), np.int64)
                x0, y0 = rng.integers(4, 16, 2)
                cls = int(rng.integers(1, 21))
                mask[y0:y0 + 12, x0:x0 + 12] = cls
                self._items.append((img, mask))
        else:
            import os
            jdir = os.path.join(data_file, "JPEGImages")
            sdir = os.path.join(data_file, "SegmentationClass")
            for f in sorted(os.listdir(jdir)):
                if not f.endswith(".npy"):
                    continue
                m = os.path.join(sdir, f)
                if os.path.exists(m):
                    self._items.append((np.load(os.path.join(jdir, f)),
                                        np.load(m).astype(np.int64)))

    def __getitem__(self, idx):
        im, mask = self._items[idx]
        if self.transform is not None:
            im = self.transform(im)
        return im, mask

    def __len__(self):
        return len(self._items)
