"""MobileNetV3 Large/Small (reference: python/paddle/vision/models/
mobilenetv3.py) — inverted residuals with squeeze-excite and hardswish."""

from __future__ import annotations

from ... import nn
from ... import ops
from .mobilenet import _make_divisible

__all__ = ["MobileNetV3Large", "MobileNetV3Small", "mobilenet_v3_large",
           "mobilenet_v3_small"]


class _SE(nn.Layer):
    def __init__(self, c, squeeze=4):
        super().__init__()
        mid = _make_divisible(c // squeeze)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, mid, 1)
        self.fc2 = nn.Conv2D(mid, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _CBA(nn.Layer):
    def __init__(self, c_in, c_out, k, stride=1, groups=1, act=None):
        super().__init__()
        self.conv = nn.Conv2D(c_in, c_out, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(c_out)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class _InvertedResidual(nn.Layer):
    def __init__(self, c_in, exp, c_out, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if exp != c_in:
            layers.append(_CBA(c_in, exp, 1, act=act))
        layers.append(_CBA(exp, exp, k, stride=stride, groups=exp, act=act))
        if se:
            layers.append(_SE(exp))
        layers.append(_CBA(exp, c_out, 1))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, SE, activation, stride) per reference config tables
_LARGE = [
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1),
]
_SMALL = [
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        act_of = {"RE": nn.ReLU, "HS": nn.Hardswish}
        c = _make_divisible(16 * scale)
        feats = [_CBA(3, c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            feats.append(_InvertedResidual(c, exp_c, out_c, k, s, se,
                                           act_of[act]))
            c = out_c
        le = _make_divisible(last_exp * scale)
        feats.append(_CBA(c, le, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(le, last_c), nn.Hardswish(),
                nn.Dropout(0.2, mode="downscale_in_infer"),
                nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, start_axis=1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    """Reference mobilenetv3.py MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    """Reference mobilenetv3.py MobileNetV3Small."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load a local "
                         "checkpoint with set_state_dict")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load a local "
                         "checkpoint with set_state_dict")
    return MobileNetV3Small(scale=scale, **kwargs)
