"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""

from __future__ import annotations

from ... import nn
from ... import ops


def channel_shuffle(x, groups):
    B, C, H, W = x.shape
    x = x.reshape([B, groups, C // groups, H, W])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([B, C, H, W])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act=nn.ReLU):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            assert in_c == out_c
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act())
        b2_in = in_c if stride > 1 else branch_c
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_stage_cfg = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        chs = _stage_cfg[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chs[0]), act_layer())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = chs[0]
        for i, reps in enumerate([4, 8, 4]):
            out_c = chs[i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act_layer)]
            for _ in range(reps - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act_layer))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, chs[4], 1, bias_attr=False),
            nn.BatchNorm2D(chs[4]), act_layer())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kwargs)
