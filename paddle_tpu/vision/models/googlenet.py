"""GoogLeNet / Inception v1 (reference: python/paddle/vision/models/
googlenet.py:107).  Same API: forward returns [out, aux1, aux2] logits."""

from __future__ import annotations

from ... import nn
from ... import ops

__all__ = ["GoogLeNet", "googlenet"]


class _ConvReLU(nn.Layer):
    def __init__(self, c_in, c_out, k, stride=1):
        super().__init__()
        self.conv = nn.Conv2D(c_in, c_out, k, stride=stride,
                              padding=(k - 1) // 2)
        self.act = nn.ReLU()

    def forward(self, x):
        return self.act(self.conv(x))


class Inception(nn.Layer):
    """Four-branch v1 block (reference googlenet.py:67)."""

    def __init__(self, c_in, f1, f3r, f3, f5r, f5, proj):
        super().__init__()
        self.b1 = _ConvReLU(c_in, f1, 1)
        self.b3 = nn.Sequential(_ConvReLU(c_in, f3r, 1), _ConvReLU(f3r, f3, 3))
        self.b5 = nn.Sequential(_ConvReLU(c_in, f5r, 1), _ConvReLU(f5r, f5, 5))
        self.bp = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _ConvReLU(c_in, proj, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b3(x), self.b5(x), self.bp(x)],
                          axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, c_in, num_classes, drop_p):
        super().__init__()
        self.pool = nn.AvgPool2D(5, stride=3)
        self.conv = _ConvReLU(c_in, 128, 1)
        self.fc1 = nn.Linear(1152, 1024)
        self.act = nn.ReLU()
        self.drop = nn.Dropout(drop_p, mode="downscale_in_infer")
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = ops.flatten(x, start_axis=1)
        x = self.drop(self.act(self.fc1(x)))
        return self.fc2(x)


class GoogLeNet(nn.Layer):
    """Reference googlenet.py:107 — returns [out, out1, out2]."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvReLU(3, 64, 7, stride=2), nn.MaxPool2D(3, stride=2),
            _ConvReLU(64, 64, 1), _ConvReLU(64, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.i3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2)
        self.i4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2)
        self.i5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.gap = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4, mode="downscale_in_infer")
            self.head = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes, 0.7)
            self.aux2 = _AuxHead(528, num_classes, 0.7)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        a4 = self.i4a(x)
        x = self.i4c(self.i4b(a4))
        d4 = self.i4d(x)
        x = self.pool4(self.i4e(d4))
        x = self.i5b(self.i5a(x))
        out, out1, out2 = x, a4, d4
        if self.with_pool:
            out = self.gap(out)
        if self.num_classes > 0:
            out = ops.flatten(self.drop(out), start_axis=1)
            out = self.head(out)
            out1 = self.aux1(out1)
            out2 = self.aux2(out2)
        return [out, out1, out2]


def googlenet(pretrained=False, **kwargs):
    """Reference googlenet.py:233 factory (pretrained weights are not
    bundled — zero-egress environment; load via set_state_dict)."""
    if pretrained:
        raise ValueError(
            "pretrained weights are not bundled in paddle_tpu; load a local "
            "checkpoint with model.set_state_dict(paddle.load(path))")
    return GoogLeNet(**kwargs)
