"""MobileNet V1/V2 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py)."""

from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act=nn.ReLU):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, stride, scale=1.0):
        super().__init__()
        mid = int(mid_c * scale)
        self.dw = ConvBNLayer(int(in_c * scale), mid, 3, stride=stride,
                              padding=1, groups=int(in_c * scale))
        self.pw = ConvBNLayer(mid, int(out_c * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale, self.num_classes, self.with_pool = scale, num_classes, with_pool
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, o, o, s, scale) for i, o, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden, 1, act=nn.ReLU6))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, act=nn.ReLU6),
            ConvBNLayer(hidden, oup, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        input_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        feats = [ConvBNLayer(3, input_c, 3, stride=2, padding=1, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(input_c, out_c,
                                              s if i == 0 else 1, t))
                input_c = out_c
        feats.append(ConvBNLayer(input_c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV2(scale=scale, **kwargs)
