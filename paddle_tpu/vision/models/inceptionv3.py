"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py:488).

Blocks follow the reference channel plan exactly (stem :36, A :90, B :166,
C :217, D :323, E :389); every conv is conv + BatchNorm + ReLU.
"""

from __future__ import annotations

import math

from ... import nn
from ... import ops
from ...nn.layer import ParamAttr
from ...nn import initializer as I

__all__ = ["InceptionV3", "inception_v3"]


class _CBR(nn.Layer):
    """conv + bn + relu (the reference's ConvNormActivation)."""

    def __init__(self, c_in, c_out, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(c_in, c_out, k, stride=stride, padding=padding,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(c_out)
        self.act = nn.ReLU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class InceptionStem(nn.Layer):
    def __init__(self):
        super().__init__()
        self.c1 = _CBR(3, 32, 3, stride=2)
        self.c2 = _CBR(32, 32, 3)
        self.c3 = _CBR(32, 64, 3, padding=1)
        self.pool1 = nn.MaxPool2D(3, stride=2)
        self.c4 = _CBR(64, 80, 1)
        self.c5 = _CBR(80, 192, 3)
        self.pool2 = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        x = self.pool1(self.c3(self.c2(self.c1(x))))
        return self.pool2(self.c5(self.c4(x)))


class InceptionA(nn.Layer):
    def __init__(self, c_in, pool_features):
        super().__init__()
        self.b1 = _CBR(c_in, 64, 1)
        self.b5 = nn.Sequential(_CBR(c_in, 48, 1), _CBR(48, 64, 5, padding=2))
        self.b3d = nn.Sequential(_CBR(c_in, 64, 1), _CBR(64, 96, 3, padding=1),
                                 _CBR(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _CBR(c_in, pool_features, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b5(x), self.b3d(x), self.bp(x)],
                          axis=1)


class InceptionB(nn.Layer):
    """Grid reduction 35->17 (reference :166)."""

    def __init__(self, c_in):
        super().__init__()
        self.b3 = _CBR(c_in, 384, 3, stride=2)
        self.b3d = nn.Sequential(_CBR(c_in, 64, 1), _CBR(64, 96, 3, padding=1),
                                 _CBR(96, 96, 3, stride=2))
        self.bp = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b3d(x), self.bp(x)], axis=1)


class InceptionC(nn.Layer):
    """Factorized 7x7 block (reference :217)."""

    def __init__(self, c_in, c7):
        super().__init__()
        self.b1 = _CBR(c_in, 192, 1)
        self.b7 = nn.Sequential(
            _CBR(c_in, c7, 1),
            _CBR(c7, c7, (1, 7), padding=(0, 3)),
            _CBR(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _CBR(c_in, c7, 1),
            _CBR(c7, c7, (7, 1), padding=(3, 0)),
            _CBR(c7, c7, (1, 7), padding=(0, 3)),
            _CBR(c7, c7, (7, 1), padding=(3, 0)),
            _CBR(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _CBR(c_in, 192, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                          axis=1)


class InceptionD(nn.Layer):
    """Grid reduction 17->8 (reference :323)."""

    def __init__(self, c_in):
        super().__init__()
        self.b3 = nn.Sequential(_CBR(c_in, 192, 1), _CBR(192, 320, 3, stride=2))
        self.b7x3 = nn.Sequential(
            _CBR(c_in, 192, 1),
            _CBR(192, 192, (1, 7), padding=(0, 3)),
            _CBR(192, 192, (7, 1), padding=(3, 0)),
            _CBR(192, 192, 3, stride=2))
        self.bp = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b7x3(x), self.bp(x)], axis=1)


class InceptionE(nn.Layer):
    """Expanded-filter-bank block (reference :389)."""

    def __init__(self, c_in):
        super().__init__()
        self.b1 = _CBR(c_in, 320, 1)
        self.b3_stem = _CBR(c_in, 384, 1)
        self.b3_a = _CBR(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _CBR(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_CBR(c_in, 448, 1),
                                      _CBR(448, 384, 3, padding=1))
        self.b3d_a = _CBR(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _CBR(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _CBR(c_in, 192, 1))

    def forward(self, x):
        s3 = self.b3_stem(x)
        s3d = self.b3d_stem(x)
        return ops.concat(
            [self.b1(x),
             ops.concat([self.b3_a(s3), self.b3_b(s3)], axis=1),
             ops.concat([self.b3d_a(s3d), self.b3d_b(s3d)], axis=1),
             self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """Reference inceptionv3.py:488."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = InceptionStem()
        blocks = []
        for c_in, pf in zip((192, 256, 288), (32, 64, 64)):
            blocks.append(InceptionA(c_in, pf))
        blocks.append(InceptionB(288))
        for c_in, c7 in zip((768,) * 4, (128, 160, 160, 192)):
            blocks.append(InceptionC(c_in, c7))
        blocks.append(InceptionD(768))
        blocks.extend([InceptionE(1280), InceptionE(2048)])
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.avg_pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2, mode="downscale_in_infer")
            stdv = 1.0 / math.sqrt(2048.0)
            self.fc = nn.Linear(
                2048, num_classes,
                weight_attr=ParamAttr(initializer=I.Uniform(-stdv, stdv)))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avg_pool(x)
        if self.num_classes > 0:
            x = ops.reshape(x, [-1, 2048])
            x = self.fc(self.dropout(x))
        return x


def inception_v3(pretrained=False, **kwargs):
    """Reference inceptionv3.py:601 factory."""
    if pretrained:
        raise ValueError(
            "pretrained weights are not bundled in paddle_tpu; load a local "
            "checkpoint with model.set_state_dict(paddle.load(path))")
    return InceptionV3(**kwargs)
