from .transforms import (  # noqa: F401
    BaseTransform, Compose, ToTensor, Normalize, Resize, CenterCrop,
    RandomCrop, RandomHorizontalFlip, RandomVerticalFlip, RandomResizedCrop,
    RandomRotation, Pad, Transpose, Grayscale, BrightnessTransform,
    ContrastTransform, SaturationTransform, HueTransform, ColorJitter,
    RandomErasing, RandomAffine, RandomPerspective,
)
from . import functional  # noqa: F401
from .functional import (  # noqa: F401
    to_tensor, normalize, resize, crop, center_crop, hflip, vflip, pad,
    rotate, adjust_brightness, adjust_contrast, adjust_hue, to_grayscale,
    erase, affine, perspective,
)
