"""vision.transforms class API (reference:
python/paddle/vision/transforms/transforms.py — BaseTransform + ~25 classes)."""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from . import functional as F


class BaseTransform:
    """Reference BaseTransform: keys-aware __call__ over (image, ...) tuples."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            keys = tuple(self.keys) + ("",) * (len(inputs) - len(self.keys))
            return tuple(self._apply_image(x) if k == "image" else x
                         for x, k in zip(inputs, keys))
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size, self.interpolation = size, interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill, self.padding_mode = fill, padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr = F._to_np(img)
        H, W = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (H < th or W < tw):
            img = F.pad(img, (max(0, tw - W), max(0, th - H)), self.fill,
                        self.padding_mode)
            arr = F._to_np(img)
            H, W = arr.shape[:2]
        top = random.randint(0, max(0, H - th))
        left = random.randint(0, max(0, W - tw))
        return F.crop(img, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = F._to_np(img)
        H, W = arr.shape[:2]
        area = H * W
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                top = random.randint(0, H - h)
                left = random.randint(0, W - w)
                return F.resize(F.crop(img, top, left, h, w), self.size,
                                self.interpolation)
        return F.resize(F.center_crop(img, min(H, W)), self.size,
                        self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation, self.expand = interpolation, expand
        self.center, self.fill = center, fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return F._to_np(img).transpose(self.order)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness), ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.ts[i]._apply_image(img)
        return img


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        arr = F._to_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
        H, W = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = H * W
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            h = int(round(np.sqrt(target * ar)))
            w = int(round(np.sqrt(target / ar)))
            if h < H and w < W:
                i = random.randint(0, H - h)
                j = random.randint(0, W - w)
                # erase via F.erase on the original so PIL in -> PIL out
                return F.erase(img, i, j, h, w, self.value, self.inplace)
        return img


class RandomAffine(BaseTransform):
    """Reference transforms.py RandomAffine: random rotation/translation/
    scale/shear inside the given ranges."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate, self.scale_rng, self.shear = translate, scale, shear
        self.interpolation, self.fill, self.center = interpolation, fill, center

    def _apply_image(self, img):
        arr = F._to_np(img)
        H, W = arr.shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * W
            ty = random.uniform(-self.translate[1], self.translate[1]) * H
        sc = random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            sh = (random.uniform(-self.shear, self.shear), 0.0)
        else:
            lo, hi = self.shear[0], self.shear[1]
            sh = (random.uniform(lo, hi), 0.0) if len(self.shear) == 2 \
                else (random.uniform(self.shear[0], self.shear[1]),
                      random.uniform(self.shear[2], self.shear[3]))
        return F.affine(img, angle, (tx, ty), sc, sh, self.interpolation,
                        self.fill, self.center)


class RandomPerspective(BaseTransform):
    """Reference transforms.py RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.distortion = prob, distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        arr = F._to_np(img)
        H, W = arr.shape[:2]
        d = self.distortion
        hw, hh = int(W * d / 2), int(H * d / 2)

        def jitter(x, y):
            return (x + random.randint(-hw, hw) if hw else x,
                    y + random.randint(-hh, hh) if hh else y)

        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [jitter(*p) for p in start]
        return F.perspective(img, start, end, self.interpolation, self.fill)
