"""vision.transforms.functional parity (reference:
python/paddle/vision/transforms/functional.py + functional_pil/_cv2/_tensor).

Host-side preprocessing: accepts PIL.Image or numpy HWC arrays, returns the
same kind (to_tensor converts to CHW float32 numpy / Tensor).  This stays off
the TPU on purpose — input pipelines run on CPU and feed device_put batches.
"""

from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np


def _is_pil(img):
    try:
        from PIL import Image

        return isinstance(img, Image.Image)
    except ImportError:  # pragma: no cover
        return False


def _to_np(img) -> np.ndarray:
    """HWC uint8/float numpy view of a PIL image or array."""
    if _is_pil(img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _like(img, arr: np.ndarray):
    """Return arr as the same kind as img (PIL in -> PIL out)."""
    if _is_pil(img):
        from PIL import Image

        if arr.shape[2] == 1:
            arr = arr[:, :, 0]
        return Image.fromarray(arr.astype(np.uint8) if arr.dtype != np.uint8
                               else arr)
    return arr


def to_tensor(pic, data_format="CHW"):
    """uint8 HWC [0,255] -> float32 CHW [0,1]; float input passes through
    unscaled (reference functional.py to_tensor semantics)."""
    raw = _to_np(pic)
    arr = raw.astype(np.float32)
    if raw.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def _interp_resize(arr: np.ndarray, h: int, w: int, interpolation="bilinear"):
    """Pure-numpy separable resize (nearest / bilinear)."""
    H, W, C = arr.shape
    if interpolation == "nearest":
        yi = np.clip((np.arange(h) + 0.5) * H / h, 0, H - 1).astype(np.int64)
        xi = np.clip((np.arange(w) + 0.5) * W / w, 0, W - 1).astype(np.int64)
        return arr[yi][:, xi]
    # bilinear, half-pixel centers
    fy = (np.arange(h) + 0.5) * H / h - 0.5
    fx = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(fy), 0, H - 1).astype(np.int64)
    x0 = np.clip(np.floor(fx), 0, W - 1).astype(np.int64)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(fy - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(fx - x0, 0.0, 1.0)[None, :, None]
    a = arr.astype(np.float32)
    top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
    bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(arr.dtype, np.floating):
        return out.astype(arr.dtype)
    return np.clip(np.round(out), 0, 255).astype(arr.dtype)


def resize(img, size, interpolation="bilinear"):
    if _is_pil(img):
        from PIL import Image

        modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                 "bicubic": Image.BICUBIC, "lanczos": Image.LANCZOS}
        if isinstance(size, int):
            w, h = img.size
            if w < h:
                ow, oh = size, int(size * h / w)
            else:
                oh, ow = size, int(size * w / h)
        else:
            oh, ow = size
        return img.resize((ow, oh), modes.get(interpolation, Image.BILINEAR))
    arr = _to_np(img)
    H, W = arr.shape[:2]
    if isinstance(size, int):
        if W < H:
            ow, oh = size, int(size * H / W)
        else:
            oh, ow = size, int(size * W / H)
    else:
        oh, ow = size
    return _interp_resize(arr, oh, ow, interpolation)


def crop(img, top, left, height, width):
    if _is_pil(img):
        return img.crop((left, top, left + width, top + height))
    return _to_np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _to_np(img)
    H, W = arr.shape[:2]
    th, tw = output_size
    top = max(0, (H - th) // 2)
    left = max(0, (W - tw) // 2)
    return crop(img, top, left, th, tw)


def hflip(img):
    if _is_pil(img):
        from PIL import Image

        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return _to_np(img)[:, ::-1].copy()


def vflip(img):
    if _is_pil(img):
        from PIL import Image

        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return _to_np(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4  # left, top, right, bottom
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    arr = _to_np(img)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, ((t, b), (l, r), (0, 0)), mode=mode, **kw)
    return _like(img, out)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    if _is_pil(img):
        return img.rotate(angle, expand=expand, center=center, fillcolor=fill)
    # numpy path: inverse-map rotation (nearest or bilinear), optional expand
    arr = _to_np(img)
    H, W = arr.shape[:2]
    # center follows the PIL (x, y) convention on both paths
    cy, cx = ((H - 1) / 2, (W - 1) / 2) if center is None else \
        (center[1], center[0])
    th = np.deg2rad(angle)
    if expand:
        # epsilon guards fp fuzz (cos(90 deg) ~ 6e-17 would bump ceil by 1)
        oh = int(np.ceil(abs(H * np.cos(th)) + abs(W * np.sin(th)) - 1e-7))
        ow = int(np.ceil(abs(H * np.sin(th)) + abs(W * np.cos(th)) - 1e-7))
        ocy, ocx = (oh - 1) / 2, (ow - 1) / 2
    else:
        oh, ow, ocy, ocx = H, W, cy, cx
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ys = cy + (yy - ocy) * np.cos(th) - (xx - ocx) * np.sin(th)
    xs = cx + (yy - ocy) * np.sin(th) + (xx - ocx) * np.cos(th)
    out = np.full((oh, ow) + arr.shape[2:], fill, dtype=arr.dtype)
    if interpolation == "bilinear":
        y0 = np.floor(ys)
        x0 = np.floor(xs)
        wy = (ys - y0)[..., None]
        wx = (xs - x0)[..., None]
        ok = (y0 >= 0) & (y0 < H - 1) & (x0 >= 0) & (x0 < W - 1)
        y0c = np.clip(y0, 0, H - 2).astype(np.int64)
        x0c = np.clip(x0, 0, W - 2).astype(np.int64)
        a = arr.astype(np.float32)
        val = (a[y0c, x0c] * (1 - wy) * (1 - wx) + a[y0c, x0c + 1] * (1 - wy) * wx
               + a[y0c + 1, x0c] * wy * (1 - wx) + a[y0c + 1, x0c + 1] * wy * wx)
        if not np.issubdtype(arr.dtype, np.floating):
            val = np.clip(np.round(val), 0, 255)
        out[ok] = val[ok].astype(arr.dtype)
    else:
        yi = np.round(ys).astype(np.int64)
        xi = np.round(xs).astype(np.int64)
        ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        out[ok] = arr[yi[ok], xi[ok]]
    return out


def adjust_brightness(img, brightness_factor):
    arr = _to_np(img).astype(np.float32) * brightness_factor
    return _like(img, np.clip(arr, 0, 255))


def adjust_contrast(img, contrast_factor):
    arr = _to_np(img).astype(np.float32)
    mean = arr.mean()
    out = (arr - mean) * contrast_factor + mean
    return _like(img, np.clip(out, 0, 255))


def adjust_saturation(img, saturation_factor):
    arr = _to_np(img).astype(np.float32)
    gray = arr.mean(axis=2, keepdims=True)
    out = gray + (arr - gray) * saturation_factor
    return _like(img, np.clip(out, 0, 255))


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    if _is_pil(img):
        hsv = np.asarray(img.convert("HSV")).copy()
        hsv[..., 0] = (hsv[..., 0].astype(np.int32) + int(hue_factor * 255)) % 256
        from PIL import Image

        return Image.fromarray(hsv, "HSV").convert(img.mode)
    arr = _to_np(img)
    from PIL import Image

    pil = Image.fromarray(arr.astype(np.uint8).squeeze())
    return np.asarray(adjust_hue(pil, hue_factor))


def to_grayscale(img, num_output_channels=1):
    arr = _to_np(img).astype(np.float32)
    gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1] + 0.114 * arr[..., 2]
            if arr.shape[2] >= 3 else arr[..., 0])
    out = np.repeat(gray[:, :, None], num_output_channels, axis=2)
    return _like(img, np.clip(out, 0, 255))


def erase(img, i, j, h, w, v, inplace=False):
    """Reference functional.erase — fill region with value(s) v.  PIL input
    returns PIL; inplace only applies to writable ndarray input."""
    pil_in = _is_pil(img)
    arr = _to_np(img) if pil_in else np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
    writable = (not pil_in) and inplace and getattr(img, "flags", None) is not None \
        and img.flags.writeable
    out = arr if writable else arr.copy()
    if chw:
        out[:, i:i + h, j:j + w] = v
    else:
        out[i:i + h, j:j + w] = v
    return _like(img, out) if pil_in else out


def _warp(arr, minv, interpolation="nearest", fill=0):
    """Inverse-map warp of an HWC array through the 3x3 matrix `minv`
    (maps OUTPUT pixel coords -> input coords)."""
    H, W, C = arr.shape
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    ones = np.ones_like(xs)
    pts = np.stack([xs, ys, ones], -1).reshape(-1, 3).astype(np.float64)
    src = pts @ np.asarray(minv, np.float64).T
    sx = src[:, 0] / src[:, 2]
    sy = src[:, 1] / src[:, 2]
    a = arr.astype(np.float32)
    if interpolation == "nearest":
        xi = np.round(sx).astype(np.int64)
        yi = np.round(sy).astype(np.int64)
        inb = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
        out = np.full((H * W, C), fill, np.float32)
        out[inb] = a[yi[inb], xi[inb]]
    else:  # bilinear
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        out = np.zeros((H * W, C), np.float32)
        wsum = np.zeros((H * W, 1), np.float32)
        for dy in (0, 1):
            for dx in (0, 1):
                xi, yi = x0 + dx, y0 + dy
                w = (np.abs(1 - dx - (sx - x0))
                     * np.abs(1 - dy - (sy - y0))).astype(np.float32)
                inb = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
                out[inb] += a[yi[inb], xi[inb]] * w[inb, None]
                wsum[inb, 0] += w[inb]
        miss = wsum[:, 0] == 0
        out[miss] = fill
        out[~miss] /= np.maximum(wsum[~miss], 1e-8)
    out = out.reshape(H, W, C)
    if np.issubdtype(arr.dtype, np.floating):
        return out.astype(arr.dtype)
    return np.clip(np.round(out), 0, 255).astype(arr.dtype)


def _affine_fwd_matrix(angle, translate, scale, shear, center):
    import math as _m
    rot = _m.radians(angle)
    sx, sy = [_m.radians(s) for s in (shear if isinstance(shear, (list,
                                      tuple)) else (shear, 0.0))]
    cx, cy = center
    tx, ty = translate

    def mat(a, b, c, d, e, f):
        return np.array([[a, b, c], [d, e, f], [0, 0, 1]], np.float64)

    T1 = mat(1, 0, cx + tx, 0, 1, cy + ty)
    R = mat(_m.cos(rot), -_m.sin(rot), 0, _m.sin(rot), _m.cos(rot), 0)
    SH = mat(1, -_m.tan(sx), 0, -_m.tan(sy), 1, 0)
    S = mat(scale, 0, 0, 0, scale, 0)
    T2 = mat(1, 0, -cx, 0, 1, -cy)
    return T1 @ R @ SH @ S @ T2


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """Affine-warp an image (reference vision/transforms/functional.py
    affine; torchvision-style parameterization)."""
    arr = _to_np(img)
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    fwd = _affine_fwd_matrix(angle, translate, scale, shear, center)
    out = _warp(arr, np.linalg.inv(fwd), interpolation, fill)
    return _like(img, out)


def _homography(startpoints, endpoints):
    """3x3 matrix mapping endpoints -> startpoints (inverse warp)."""
    A, b = [], []
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        b.append(sx)
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.append(sy)
    h = np.linalg.solve(np.asarray(A, np.float64), np.asarray(b, np.float64))
    return np.append(h, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective-warp: the quad `startpoints` maps to `endpoints`
    (reference functional.py perspective)."""
    arr = _to_np(img)
    minv = _homography(startpoints, endpoints)
    return _like(img, _warp(arr, minv, interpolation, fill))
