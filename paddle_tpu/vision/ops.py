"""vision.ops parity (reference: python/paddle/vision/ops.py — nms,
roi_align, roi_pool, box utilities, deform_conv2d).

TPU note: detection post-processing (nms) is host-side numpy — dynamic output
sizes don't belong under jit; roi_align/roi_pool are pure-jnp gather programs
that XLA vectorizes."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op, to_tensor

__all__ = ["nms", "roi_align", "roi_pool", "box_area", "box_iou",
           "deform_conv2d", "DeformConv2D", "psroi_pool", "RoIAlign", "RoIPool", "PSRoIPool",
           "box_coder", "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
           "generate_proposals", "distribute_fpn_proposals", "read_file",
           "decode_jpeg",
]


def _raw(x):
    return np.asarray(x.data) if isinstance(x, Tensor) else np.asarray(x)


def box_area(boxes):
    b = _raw(boxes)
    return to_tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def box_iou(boxes1, boxes2):
    a, b = _raw(boxes1), _raw(boxes2)
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return to_tensor(inter / (area1[:, None] + area2[None] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference vision/ops.py nms: greedy suppression, optional per-category."""
    b = _raw(boxes)
    n = len(b)
    s = _raw(scores) if scores is not None else np.arange(n, 0, -1, dtype=np.float32)

    def _greedy(idxs):
        order = idxs[np.argsort(-s[idxs], kind="stable")]
        keep = []
        suppressed = np.zeros(n, bool)
        for i in order:
            if suppressed[i]:
                continue
            keep.append(i)
            xx1 = np.maximum(b[i, 0], b[order, 0])
            yy1 = np.maximum(b[i, 1], b[order, 1])
            xx2 = np.minimum(b[i, 2], b[order, 2])
            yy2 = np.minimum(b[i, 3], b[order, 3])
            w = np.clip(xx2 - xx1, 0, None)
            h = np.clip(yy2 - yy1, 0, None)
            inter = w * h
            a_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
            a_o = (b[order, 2] - b[order, 0]) * (b[order, 3] - b[order, 1])
            iou = inter / (a_i + a_o - inter + 1e-10)
            suppressed[order[iou > iou_threshold]] = True
            suppressed[i] = False
        return np.asarray(keep, np.int64)

    if category_idxs is None:
        keep = _greedy(np.arange(n))
    else:
        cidx = _raw(category_idxs)
        cats = categories if categories is not None else np.unique(cidx)
        parts = [
            _greedy(np.flatnonzero(cidx == c)) for c in cats
        ]
        keep = np.concatenate([p for p in parts if len(p)]) if parts else \
            np.empty(0, np.int64)
        keep = keep[np.argsort(-s[keep], kind="stable")]
    if top_k is not None:
        keep = keep[:top_k]
    return to_tensor(keep)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign: bilinear sampling on a regular grid inside each box."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xs = [x if isinstance(x, Tensor) else to_tensor(x),
          boxes if isinstance(boxes, Tensor) else to_tensor(boxes)]
    bn = _raw(boxes_num).astype(np.int64)
    batch_idx = np.repeat(np.arange(len(bn)), bn)
    ratio = 1 if sampling_ratio <= 0 else sampling_ratio

    def f(feat, rois):
        off = 0.5 if aligned else 0.0
        rois = rois.astype(jnp.float32) * spatial_scale - off
        H, W = feat.shape[2], feat.shape[3]

        def one(bi, roi):
            x1, y1, x2, y2 = roi
            rh = jnp.maximum(y2 - y1, 1e-4) / ph
            rw = jnp.maximum(x2 - x1, 1e-4) / pw
            # sample `ratio` points per bin per dim, average
            iy = (jnp.arange(ph * ratio) + 0.5) / ratio
            ix = (jnp.arange(pw * ratio) + 0.5) / ratio
            ys = y1 + iy * rh
            xcs = x1 + ix * rw
            y0 = jnp.clip(jnp.floor(ys), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xcs), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            wy = jnp.clip(ys - y0, 0, 1)
            wx = jnp.clip(xcs - x0, 0, 1)
            y0 = y0.astype(jnp.int32)
            x0 = x0.astype(jnp.int32)
            fm = feat[bi]  # (C, H, W)
            top = fm[:, y0][:, :, x0] * (1 - wx) + fm[:, y0][:, :, x1i] * wx
            bot = fm[:, y1i][:, :, x0] * (1 - wx) + fm[:, y1i][:, :, x1i] * wx
            vals = top * (1 - wy[:, None]) + bot * wy[:, None]  # (C, phr, pwr)
            C = vals.shape[0]
            vals = vals.reshape(C, ph, ratio, pw, ratio).mean((2, 4))
            return vals

        return jax.vmap(one)(jnp.asarray(batch_idx), rois)

    return apply_op("roi_align", f, *xs)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoIPool: max over bins (quantized)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    feat = _raw(x)
    rois = _raw(boxes) * spatial_scale
    bn = _raw(boxes_num).astype(np.int64)
    batch_idx = np.repeat(np.arange(len(bn)), bn)
    N, C, H, W = feat.shape
    out = np.zeros((len(rois), C, ph, pw), feat.dtype)
    for r, (bi, roi) in enumerate(zip(batch_idx, rois)):
        x1, y1, x2, y2 = np.round(roi).astype(np.int64)
        # clamp to the feature map; negative starts would wrap as slices
        x1 = int(np.clip(x1, 0, W - 1))
        y1 = int(np.clip(y1, 0, H - 1))
        x2 = int(np.clip(x2, x1 + 1, W))
        y2 = int(np.clip(y2, y1 + 1, H))
        hs = np.linspace(y1, y2, ph + 1).astype(np.int64)
        ws = np.linspace(x1, x2, pw + 1).astype(np.int64)
        for i in range(ph):
            for j in range(pw):
                ys, ye = hs[i], max(hs[i + 1], hs[i] + 1)
                xs_, xe = ws[j], max(ws[j + 1], ws[j] + 1)
                patch = feat[bi, :, min(ys, H - 1):min(ye, H),
                             min(xs_, W - 1):min(xe, W)]
                if patch.size:
                    out[r, :, i, j] = patch.max((1, 2))
    return to_tensor(out)


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d: data-dependent gather conv — planned as a Pallas "
        "kernel; use roi_align/standard convs meanwhile")


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError("DeformConv2D — see deform_conv2d")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Position-sensitive RoI pooling (reference vision/ops.py psroi_pool):
    input channels C = out_c * ph * pw; bin (i, j) average-pools its own
    channel slice."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xs = [x if isinstance(x, Tensor) else to_tensor(x),
          boxes if isinstance(boxes, Tensor) else to_tensor(boxes)]
    bn = _raw(boxes_num).astype(np.int64)
    batch_idx = np.repeat(np.arange(len(bn)), bn)
    C = int(_raw(x).shape[1])
    if C % (ph * pw):
        raise ValueError(
            f"psroi_pool: channels {C} not divisible by {ph}x{pw}")
    out_c = C // (ph * pw)

    def f(feat, rois):
        H, W = feat.shape[2], feat.shape[3]
        rois = rois.astype(jnp.float32) * spatial_scale

        def one(bi, roi):
            x1, y1, x2, y2 = roi
            rh = jnp.maximum(y2 - y1, 0.1) / ph
            rw = jnp.maximum(x2 - x1, 0.1) / pw
            fm = feat[bi].reshape(out_c, ph * pw, H, W)
            outs = []
            # average over a fixed 4x4 sample grid per bin (static shapes)
            g = 4
            for i in range(ph):
                for j in range(pw):
                    ys = y1 + (i + (jnp.arange(g) + 0.5) / g) * rh
                    xs_ = x1 + (j + (jnp.arange(g) + 0.5) / g) * rw
                    yi = jnp.clip(jnp.round(ys), 0, H - 1).astype(jnp.int32)
                    xi = jnp.clip(jnp.round(xs_), 0, W - 1).astype(jnp.int32)
                    patch = fm[:, i * pw + j][:, yi][:, :, xi]  # (out_c, g, g)
                    outs.append(patch.mean((1, 2)))
            return jnp.stack(outs, 1).reshape(out_c, ph, pw)

        return jax.vmap(one)(jnp.asarray(batch_idx), rois)

    return apply_op("psroi_pool", f, *xs)


class RoIAlign:
    """Layer form of roi_align (reference vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._o, self._s = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._o, self._s,
                         aligned=aligned)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self._o, self._s = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._o, self._s)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self._o, self._s = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._o, self._s)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference vision/ops.py
    box_coder, the SSD convention)."""
    pb = _raw(prior_box).astype(np.float32)
    tv = _raw(target_box)
    if isinstance(prior_box_var, (list, tuple)):
        pbv = np.asarray(prior_box_var, np.float32)
    elif prior_box_var is None:
        pbv = np.ones(4, np.float32)
    else:
        pbv = _raw(prior_box_var).astype(np.float32)
    norm = 0.0 if box_normalized else 1.0

    def f(tb):
        pw = pb[:, 2] - pb[:, 0] + norm
        phh = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + phh * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tcx[:, None] - pcx[None]) / pw[None],
                (tcy[:, None] - pcy[None]) / phh[None],
                jnp.log(tw[:, None] / pw[None]),
                jnp.log(th[:, None] / phh[None])], -1)
            if pbv.ndim == 1 and pbv.size == 4:
                return out / pbv.reshape(1, 1, 4)      # per-coordinate
            if pbv.ndim == 2:                          # per-prior variance
                return out / pbv[None, :, :]
            return out
        # decode: tb (N, M, 4) deltas against priors on `axis`
        d = tb * (pbv if pbv.ndim == 1 else pbv[:, None, :]) \
            if pbv.size else tb
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (v[None, :] for v in (pw, phh, pcx, pcy))
        else:
            pw_, ph_, pcx_, pcy_ = (v[:, None] for v in (pw, phh, pcx, pcy))
        cx = d[..., 0] * pw_ + pcx_
        cy = d[..., 1] * ph_ + pcy_
        w = jnp.exp(d[..., 2]) * pw_
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], -1)

    return apply_op("box_coder", f,
                    target_box if isinstance(target_box, Tensor)
                    else to_tensor(tv))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior/anchor boxes for one feature map (reference vision/ops.py
    prior_box).  Returns (boxes (H, W, A, 4), variances same shape)."""
    fh, fw = int(_raw(input).shape[2]), int(_raw(input).shape[3])
    ih, iw = int(_raw(image).shape[2]), int(_raw(image).shape[3])
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            boxes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            for mx in max_sizes:
                s = np.sqrt(ms * mx)
                boxes.append((s, s))
    A = len(boxes)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    out = np.zeros((fh, fw, A, 4), np.float32)
    for a, (bw, bh) in enumerate(boxes):
        out[:, :, a, 0] = (cx[None, :] - bw / 2) / iw
        out[:, :, a, 1] = (cy[:, None] - bh / 2) / ih
        out[:, :, a, 2] = (cx[None, :] + bw / 2) / iw
        out[:, :, a, 3] = (cy[:, None] + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return to_tensor(out), to_tensor(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.005,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode a YOLOv3 head into boxes + scores (reference vision/ops.py
    yolo_box).  x: (B, A*(5+C), H, W); returns (boxes (B, A*H*W, 4),
    scores (B, A*H*W, C))."""
    xs = x if isinstance(x, Tensor) else to_tensor(x)
    imgs = _raw(img_size).astype(np.float32)
    A = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(A, 2)

    def f(xr):
        B, _, H, W = xr.shape
        v = xr.reshape(B, A, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)
        gy = jnp.arange(H, dtype=jnp.float32)
        sx = jax.nn.sigmoid(v[:, :, 0]) * scale_x_y \
            - (scale_x_y - 1.0) / 2.0
        sy = jax.nn.sigmoid(v[:, :, 1]) * scale_x_y \
            - (scale_x_y - 1.0) / 2.0
        bx = (gx[None, None, None, :] + sx) / W
        by = (gy[None, None, :, None] + sy) / H
        bw = jnp.exp(v[:, :, 2]) * anc[None, :, 0, None, None] \
            / (W * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * anc[None, :, 1, None, None] \
            / (H * downsample_ratio)
        obj = jax.nn.sigmoid(v[:, :, 4])
        cls = jax.nn.sigmoid(v[:, :, 5:])
        score = obj[:, :, None] * cls                   # (B, A, C, H, W)
        iw = imgs[:, 1][:, None, None, None]
        ih = imgs[:, 0][:, None, None, None]
        x1 = (bx - bw / 2) * iw
        y1 = (by - bh / 2) * ih
        x2 = (bx + bw / 2) * iw
        y2 = (by + bh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(B, -1, 4)
        scores = score.transpose(0, 1, 3, 4, 2).reshape(B, -1, class_num)
        keep = (obj.reshape(B, -1) > conf_thresh)[..., None]
        return boxes * keep, scores * keep
    return apply_op("yolo_box", f, xs)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (reference vision/ops.py yolo_loss): coord MSE
    + objectness/class BCE against anchor-matched targets (simplified
    single-scale matching, numerically reasonable rather than kernel-
    bitwise)."""
    xs = x if isinstance(x, Tensor) else to_tensor(x)
    gb = gt_box if isinstance(gt_box, Tensor) else to_tensor(gt_box)
    gl = gt_label if isinstance(gt_label, Tensor) else to_tensor(gt_label)
    A = len(anchor_mask)
    anc = np.asarray(anchors, np.float32).reshape(-1, 2)[list(anchor_mask)]

    def f(xr, gbr, glr):
        B, _, H, W = xr.shape
        v = xr.reshape(B, A, 5 + class_num, H, W)
        obj_logit = v[:, :, 4]
        # build objectness target: cell containing each gt center, best
        # anchor by wh-IoU
        cx = (gbr[:, :, 0] * W).astype(jnp.int32).clip(0, W - 1)
        cy = (gbr[:, :, 1] * H).astype(jnp.int32).clip(0, H - 1)
        gw = gbr[:, :, 2] * W * downsample_ratio
        gh = gbr[:, :, 3] * H * downsample_ratio
        inter = jnp.minimum(gw[..., None], anc[None, None, :, 0]) \
            * jnp.minimum(gh[..., None], anc[None, None, :, 1])
        union = gw[..., None] * gh[..., None] \
            + anc[None, None, :, 0] * anc[None, None, :, 1] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)  # (B, G)
        valid = (gbr[:, :, 2] > 0) & (gbr[:, :, 3] > 0)
        tgt = jnp.zeros((B, A, H, W))
        bidx = jnp.arange(B)[:, None].repeat(gbr.shape[1], 1)
        tgt = tgt.at[bidx, best, cy, cx].max(valid.astype(jnp.float32))
        bce = jnp.maximum(obj_logit, 0) - obj_logit * tgt \
            + jnp.log1p(jnp.exp(-jnp.abs(obj_logit)))
        obj_loss = bce.sum((1, 2, 3))
        # coordinate loss at matched cells
        sxy = jax.nn.sigmoid(v[:, :, 0:2])
        pred_x = sxy[:, :, 0][bidx, best, cy, cx]
        pred_y = sxy[:, :, 1][bidx, best, cy, cx]
        tx = gbr[:, :, 0] * W - jnp.floor(gbr[:, :, 0] * W)
        ty = gbr[:, :, 1] * H - jnp.floor(gbr[:, :, 1] * H)
        coord = (((pred_x - tx) ** 2 + (pred_y - ty) ** 2)
                 * valid).sum(-1)
        # class BCE at matched cells
        cl = v[:, :, 5:][bidx, best, :, cy, cx]          # (B, G, C)
        onehot = jax.nn.one_hot(glr, class_num)
        smooth = 1.0 / class_num if use_label_smooth else 0.0
        tcls = onehot * (1 - smooth) + smooth / 2
        cbce = (jnp.maximum(cl, 0) - cl * tcls
                + jnp.log1p(jnp.exp(-jnp.abs(cl)))).sum(-1)
        cls_loss = (cbce * valid).sum(-1)
        return obj_loss + coord + cls_loss

    return apply_op("yolo_loss", f, xs, gb, gl, nondiff=(1, 2))


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference vision/ops.py matrix_nms; SOLOv2): decay each
    box's score by its IoU with higher-scoring same-class boxes."""
    bb = np.asarray(_raw(bboxes), np.float32)
    sc = np.asarray(_raw(scores), np.float32)
    B, C, N = sc.shape
    all_out, all_idx, nums = [], [], []
    for b in range(B):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[b, c]
            keep = np.nonzero(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            boxes_c = bb[b, order]
            ss = s[order]
            n = len(order)
            x1, y1, x2, y2 = boxes_c.T
            area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
            ix1 = np.maximum(x1[:, None], x1[None, :])
            iy1 = np.maximum(y1[:, None], y1[None, :])
            ix2 = np.minimum(x2[:, None], x2[None, :])
            iy2 = np.minimum(y2[:, None], y2[None, :])
            inter = (np.maximum(ix2 - ix1, 0)
                     * np.maximum(iy2 - iy1, 0))
            iou = inter / np.maximum(area[:, None] + area[None, :] - inter,
                                     1e-9)
            iou = np.triu(iou, 1)
            iou_cmax = iou.max(0)
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - iou_cmax[None, :] ** 2)
                               / gaussian_sigma).min(0)
            else:
                decay = ((1 - iou) / np.maximum(1 - iou_cmax[None, :],
                                                1e-9)).min(0)
            ds = ss * decay
            for i in range(n):
                if ds[i] >= post_threshold:
                    dets.append((c, ds[i], *boxes_c[i], order[i]))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        nums.append(len(dets))
        for d in dets:
            all_out.append(d[:6])
            all_idx.append(b * N + d[6])
    out = to_tensor(np.asarray(all_out, np.float32).reshape(-1, 6))
    res = [out]
    if return_index:
        res.append(to_tensor(np.asarray(all_idx, np.int64)))
    if return_rois_num:
        res.append(to_tensor(np.asarray(nums, np.int32)))
    return tuple(res) if len(res) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference vision/ops.py
    generate_proposals): decode deltas, clip, filter, NMS, top-k."""
    sc = np.asarray(_raw(scores), np.float32)      # (B, A, H, W)
    bd = np.asarray(_raw(bbox_deltas), np.float32)  # (B, 4A, H, W)
    ims = np.asarray(_raw(img_size), np.float32)
    anc = np.asarray(_raw(anchors), np.float32).reshape(-1, 4)
    var = np.asarray(_raw(variances), np.float32).reshape(-1, 4)
    B = sc.shape[0]
    outs, rnums, oscores = [], [], []
    for b in range(B):
        s = sc[b].transpose(1, 2, 0).reshape(-1)
        d = bd[b].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], anc[order % len(anc)], \
            var[order % len(var)]
        aw = a[:, 2] - a[:, 0]
        ah = a[:, 3] - a[:, 1]
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = d[:, 0] * v[:, 0] * aw + acx
        cy = d[:, 1] * v[:, 1] * ah + acy
        w = np.exp(np.clip(d[:, 2] * v[:, 2], -10, 10)) * aw
        h = np.exp(np.clip(d[:, 3] * v[:, 3], -10, 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], 1)
        H_, W_ = ims[b, 0], ims[b, 1]
        boxes[:, 0::2] = boxes[:, 0::2].clip(0, W_ - 1)
        boxes[:, 1::2] = boxes[:, 1::2].clip(0, H_ - 1)
        ok = ((boxes[:, 2] - boxes[:, 0] >= min_size)
              & (boxes[:, 3] - boxes[:, 1] >= min_size))
        boxes, s = boxes[ok], s[ok]
        keep = np.asarray(_raw(nms(to_tensor(boxes), nms_thresh,
                                   to_tensor(s))))[:post_nms_top_n]
        outs.append(boxes[keep])
        oscores.append(s[keep])
        rnums.append(len(keep))
    rois = to_tensor(np.concatenate(outs) if outs
                     else np.zeros((0, 4), np.float32))
    rscores = to_tensor(np.concatenate(oscores) if oscores
                        else np.zeros((0,), np.float32))
    if return_rois_num:
        return rois, rscores, to_tensor(np.asarray(rnums, np.int32))
    return rois, rscores


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference vision/ops.py
    distribute_fpn_proposals)."""
    rois = np.asarray(_raw(fpn_rois), np.float32)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.maximum(w * h, 1e-9))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-9)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, restore = [], []
    order = []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        multi.append(to_tensor(rois[idx]))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    nums = [to_tensor(np.asarray([len(np.asarray(_raw(m)))], np.int32))
            for m in multi] if rois_num is not None else None
    res = [multi, to_tensor(restore.reshape(-1, 1))]
    if rois_num is not None:
        res.append(nums)
    return tuple(res)


def read_file(filename, name=None):
    """File bytes as a uint8 tensor (reference vision/ops.py read_file)."""
    with open(filename, "rb") as f:
        return to_tensor(np.frombuffer(f.read(), np.uint8).copy())


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes to (C, H, W) uint8 (reference vision/ops.py
    decode_jpeg; uses PIL on host — no GPU nvjpeg here)."""
    import io
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "decode_jpeg needs Pillow for host JPEG decoding") from e
    raw = np.asarray(_raw(x), np.uint8).tobytes()
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return to_tensor(arr.copy())
