"""vision.ops parity (reference: python/paddle/vision/ops.py — nms,
roi_align, roi_pool, box utilities, deform_conv2d).

TPU note: detection post-processing (nms) is host-side numpy — dynamic output
sizes don't belong under jit; roi_align/roi_pool are pure-jnp gather programs
that XLA vectorizes."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op, to_tensor

__all__ = ["nms", "roi_align", "roi_pool", "box_area", "box_iou",
           "deform_conv2d", "DeformConv2D"]


def _raw(x):
    return np.asarray(x.data) if isinstance(x, Tensor) else np.asarray(x)


def box_area(boxes):
    b = _raw(boxes)
    return to_tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def box_iou(boxes1, boxes2):
    a, b = _raw(boxes1), _raw(boxes2)
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return to_tensor(inter / (area1[:, None] + area2[None] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference vision/ops.py nms: greedy suppression, optional per-category."""
    b = _raw(boxes)
    n = len(b)
    s = _raw(scores) if scores is not None else np.arange(n, 0, -1, dtype=np.float32)

    def _greedy(idxs):
        order = idxs[np.argsort(-s[idxs], kind="stable")]
        keep = []
        suppressed = np.zeros(n, bool)
        for i in order:
            if suppressed[i]:
                continue
            keep.append(i)
            xx1 = np.maximum(b[i, 0], b[order, 0])
            yy1 = np.maximum(b[i, 1], b[order, 1])
            xx2 = np.minimum(b[i, 2], b[order, 2])
            yy2 = np.minimum(b[i, 3], b[order, 3])
            w = np.clip(xx2 - xx1, 0, None)
            h = np.clip(yy2 - yy1, 0, None)
            inter = w * h
            a_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
            a_o = (b[order, 2] - b[order, 0]) * (b[order, 3] - b[order, 1])
            iou = inter / (a_i + a_o - inter + 1e-10)
            suppressed[order[iou > iou_threshold]] = True
            suppressed[i] = False
        return np.asarray(keep, np.int64)

    if category_idxs is None:
        keep = _greedy(np.arange(n))
    else:
        cidx = _raw(category_idxs)
        cats = categories if categories is not None else np.unique(cidx)
        parts = [
            _greedy(np.flatnonzero(cidx == c)) for c in cats
        ]
        keep = np.concatenate([p for p in parts if len(p)]) if parts else \
            np.empty(0, np.int64)
        keep = keep[np.argsort(-s[keep], kind="stable")]
    if top_k is not None:
        keep = keep[:top_k]
    return to_tensor(keep)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign: bilinear sampling on a regular grid inside each box."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xs = [x if isinstance(x, Tensor) else to_tensor(x),
          boxes if isinstance(boxes, Tensor) else to_tensor(boxes)]
    bn = _raw(boxes_num).astype(np.int64)
    batch_idx = np.repeat(np.arange(len(bn)), bn)
    ratio = 1 if sampling_ratio <= 0 else sampling_ratio

    def f(feat, rois):
        off = 0.5 if aligned else 0.0
        rois = rois.astype(jnp.float32) * spatial_scale - off
        H, W = feat.shape[2], feat.shape[3]

        def one(bi, roi):
            x1, y1, x2, y2 = roi
            rh = jnp.maximum(y2 - y1, 1e-4) / ph
            rw = jnp.maximum(x2 - x1, 1e-4) / pw
            # sample `ratio` points per bin per dim, average
            iy = (jnp.arange(ph * ratio) + 0.5) / ratio
            ix = (jnp.arange(pw * ratio) + 0.5) / ratio
            ys = y1 + iy * rh
            xcs = x1 + ix * rw
            y0 = jnp.clip(jnp.floor(ys), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xcs), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            wy = jnp.clip(ys - y0, 0, 1)
            wx = jnp.clip(xcs - x0, 0, 1)
            y0 = y0.astype(jnp.int32)
            x0 = x0.astype(jnp.int32)
            fm = feat[bi]  # (C, H, W)
            top = fm[:, y0][:, :, x0] * (1 - wx) + fm[:, y0][:, :, x1i] * wx
            bot = fm[:, y1i][:, :, x0] * (1 - wx) + fm[:, y1i][:, :, x1i] * wx
            vals = top * (1 - wy[:, None]) + bot * wy[:, None]  # (C, phr, pwr)
            C = vals.shape[0]
            vals = vals.reshape(C, ph, ratio, pw, ratio).mean((2, 4))
            return vals

        return jax.vmap(one)(jnp.asarray(batch_idx), rois)

    return apply_op("roi_align", f, *xs)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoIPool: max over bins (quantized)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    feat = _raw(x)
    rois = _raw(boxes) * spatial_scale
    bn = _raw(boxes_num).astype(np.int64)
    batch_idx = np.repeat(np.arange(len(bn)), bn)
    N, C, H, W = feat.shape
    out = np.zeros((len(rois), C, ph, pw), feat.dtype)
    for r, (bi, roi) in enumerate(zip(batch_idx, rois)):
        x1, y1, x2, y2 = np.round(roi).astype(np.int64)
        # clamp to the feature map; negative starts would wrap as slices
        x1 = int(np.clip(x1, 0, W - 1))
        y1 = int(np.clip(y1, 0, H - 1))
        x2 = int(np.clip(x2, x1 + 1, W))
        y2 = int(np.clip(y2, y1 + 1, H))
        hs = np.linspace(y1, y2, ph + 1).astype(np.int64)
        ws = np.linspace(x1, x2, pw + 1).astype(np.int64)
        for i in range(ph):
            for j in range(pw):
                ys, ye = hs[i], max(hs[i + 1], hs[i] + 1)
                xs_, xe = ws[j], max(ws[j + 1], ws[j] + 1)
                patch = feat[bi, :, min(ys, H - 1):min(ye, H),
                             min(xs_, W - 1):min(xe, W)]
                if patch.size:
                    out[r, :, i, j] = patch.max((1, 2))
    return to_tensor(out)


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d: data-dependent gather conv — planned as a Pallas "
        "kernel; use roi_align/standard convs meanwhile")


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError("DeformConv2D — see deform_conv2d")
