"""paddle.vision parity (reference: python/paddle/vision/, ~14.6k LoC —
datasets, transforms, models, ops).  SURVEY.md C48.

TPU notes: transforms produce contiguous float32/uint8 numpy (host-side, feed
into jax.device_put batches); models are eager nn.Layers whose convs lower to
XLA convolutions on the MXU (NCHW layout like the reference API; XLA picks the
TPU-native layout internally)."""

from __future__ import annotations

from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401

from .models import (  # noqa: F401
    LeNet, AlexNet, VGG, vgg11, vgg13, vgg16, vgg19,
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2,
    MobileNetV1, mobilenet_v1, MobileNetV2, mobilenet_v2,
    SqueezeNet, squeezenet1_0, squeezenet1_1,
    DenseNet, densenet121, densenet161, densenet169, densenet201, densenet264,
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x1_0, shufflenet_v2_swish,
)

_image_backend = "pil"


def set_image_backend(backend: str) -> None:
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    from PIL import Image

    return Image.open(path)
