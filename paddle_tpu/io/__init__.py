"""paddle.io parity: Dataset / DataLoader / Samplers.

Reference: python/paddle/io/ (reader.py:216 DataLoader, dataloader_iter.py).
TPU-native notes: the loader's job is to keep the XLA feed ahead of the device —
a background-thread prefetcher with pinned numpy batches (double buffering)
replaces the reference's multiprocess DataLoaderIter; heavy decode work can go
through the native C++ dataio library (paddle_tpu/dataio) when present.
"""

from __future__ import annotations

import itertools
import math
import queue
import threading

import numpy as np

from ..tensor import Tensor, to_tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset", "ChainDataset",
    "Subset", "random_split", "Sampler", "SequenceSampler", "RandomSampler",
    "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler", "DataLoader",
    "get_worker_info", "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [t if isinstance(t, Tensor) else to_tensor(t) for t in tensors]
        assert all(t.shape[0] == self.tensors[0].shape[0] for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        total = len(dataset)
        lengths = [int(math.floor(total * l)) for l in lengths]
        lengths[-1] += total - sum(lengths)
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, size=self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), size=self.num_samples, replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks (reference:
    io/dataloader/batch_sampler.py DistributedBatchSampler).  On the TPU build,
    rank/nranks default to the 'data' mesh axis coordinates."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False,
                 drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size
            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id=0, num_workers=1, dataset=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.generic)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class _MPUnpicklable(Exception):
    """Dataset/collate not picklable for spawned workers."""


def _mp_worker_main(payload, worker_id, idx_q, out_q):
    # loader workers do HOST-side work only — pin them to the CPU platform
    # before anything imports jax (env alone is not enough: a wedged TPU
    # plugin can block the first dispatch even when unselected)
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — jax-free datasets don't need this
        pass
    import pickle

    dataset, collate, init_fn = pickle.loads(payload)
    if init_fn is not None:
        init_fn(worker_id)
    while True:
        item = idx_q.get()
        if item is None:
            break
        bid, indices = item
        try:
            out_q.put((bid, None, collate([dataset[i] for i in indices])))
        except Exception as e:  # noqa: BLE001
            out_q.put((bid, f"{type(e).__name__}: {e}", None))


class DataLoader:
    """Prefetching loader (reference: io/reader.py:216): num_workers=0 is
    synchronous, otherwise SPAWNED worker processes fetch and collate
    (map-style datasets; iterable datasets use a prefetch thread)."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)

    def _gen_batches(self):
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._gen_batches()
            return
        if not self._iterable_mode:
            # multiprocess workers (reference: io/dataloader/dataloader_iter.py
            # :358 _DataLoaderIterMultiProcess) — real parallelism for
            # Python-bound datasets so the device feed never starves
            try:
                yield from self._mp_batches()
                return
            except (_MPUnpicklable, ImportError):
                pass  # unpicklable dataset/collate: thread prefetch below
        # background prefetch thread (double buffering toward the device feed)
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor * max(self.num_workers, 1))
        sentinel = object()
        error_holder = []

        def producer():
            try:
                for b in self._gen_batches():
                    q.put(b)
            except BaseException as e:  # noqa: BLE001
                error_holder.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if error_holder:
                    raise error_holder[0]
                break
            yield item

    def _mp_batches(self):
        """Spawned worker processes fetch+collate batches; the parent
        reorders by batch id so iteration order matches num_workers=0.

        Spawn (not fork): forking after XLA's thread pools exist can
        deadlock; spawned workers import only the dataset's module."""
        import multiprocessing as mp
        import pickle

        ctx = mp.get_context("spawn")
        batches = list(self.batch_sampler)
        try:
            payload = pickle.dumps(
                (self.dataset, self.collate_fn, self.worker_init_fn))
        except Exception as e:  # noqa: BLE001
            raise _MPUnpicklable(str(e)) from e
        idx_q = ctx.Queue()
        out_q = ctx.Queue(maxsize=self.prefetch_factor * self.num_workers)
        for i, b in enumerate(batches):
            idx_q.put((i, list(b)))
        workers = []
        for wid in range(self.num_workers):
            idx_q.put(None)  # one sentinel per worker
            w = ctx.Process(target=_mp_worker_main,
                            args=(payload, wid, idx_q, out_q), daemon=True)
            w.start()
            workers.append(w)
        try:
            import queue as _queue
            pending = {}
            want = 0
            got = 0
            while got < len(batches):
                try:
                    bid, err, data = out_q.get(timeout=5.0)
                except _queue.Empty:
                    dead = [w.exitcode for w in workers
                            if not w.is_alive() and w.exitcode != 0]
                    if dead:
                        raise RuntimeError(
                            f"DataLoader worker died (exit codes {dead}) "
                            "before finishing its batches")
                    continue
                got += 1
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                pending[bid] = data
                while want in pending:
                    yield pending.pop(want)
                    want += 1
        finally:
            for w in workers:
                if w.is_alive():
                    w.terminate()
                w.join()
