"""Token-file dataset over the native (C++) data-IO core.

Reference analog: the C++ DataFeed/Dataset pipeline
(paddle/fluid/framework/data_feed.cc InMemoryDataFeed, data_set.cc shuffle)
— the file-ingestion + shuffle capability the Python-level DataLoader lacks.
A flat binary file of fixed-width token rows (the standard pretraining
pack format) is mmap'd in C++ (native/dataio.cpp); epochs shuffle with a
seeded Fisher-Yates; batches come back as ready int32 numpy blocks, so the
accelerator feed never waits on a Python inner loop.  Falls back to a
numpy memmap when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional

import numpy as np

from .. import native

__all__ = ["TokenFileDataset", "write_token_file"]


def write_token_file(path: str, tokens: np.ndarray) -> str:
    """Pack a (rows, row_len) int array into the flat binary format."""
    arr = np.ascontiguousarray(tokens)
    if arr.dtype not in (np.int32, np.uint16):
        arr = arr.astype(np.int32)
    arr.tofile(path)
    return path


class TokenFileDataset:
    """Iterable over shuffled (batch, row_len) int32 batches of a packed
    token file.  Deterministic per (seed, epoch)."""

    def __init__(self, path: str, row_len: int, batch_size: int,
                 dtype: str = "int32", shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False):
        self.path = path
        self.row_len = int(row_len)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.drop_last = drop_last
        self.itemsize = {"int32": 4, "uint16": 2}[dtype]
        self._dtype = dtype
        self._epoch = 0
        self._lib = native.load("dataio")
        if self._lib is not None:
            self._bind(self._lib)
            self._h = self._lib.dataio_open(
                path.encode(), self.row_len, self.itemsize)
            if not self._h:
                raise FileNotFoundError(f"cannot open token file {path}")
            self._n = self._lib.dataio_num_rows(self._h)
            self._sampler = self._lib.dataio_sampler_new(self._h, self.seed)
        else:  # pure-numpy fallback (no toolchain)
            self._mm = np.memmap(path, dtype=self._dtype, mode="r")
            self._n = self._mm.shape[0] // self.row_len
            self._h = self._sampler = None

    @staticmethod
    def _bind(lib):
        lib.dataio_open.restype = ctypes.c_void_p
        lib.dataio_open.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int]
        lib.dataio_num_rows.restype = ctypes.c_int64
        lib.dataio_num_rows.argtypes = [ctypes.c_void_p]
        lib.dataio_sampler_new.restype = ctypes.c_void_p
        lib.dataio_sampler_new.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dataio_sampler_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                             ctypes.c_int]
        lib.dataio_next_batch.restype = ctypes.c_int64
        lib.dataio_next_batch.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_int64, ctypes.c_void_p]
        lib.dataio_gather.restype = ctypes.c_int64
        lib.dataio_gather.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int64, ctypes.c_void_p]
        lib.dataio_sampler_free.argtypes = [ctypes.c_void_p]
        lib.dataio_close.argtypes = [ctypes.c_void_p]

    def __len__(self):
        q, r = divmod(self._n, self.batch_size)
        return q if (self.drop_last or r == 0) else q + 1

    @property
    def num_rows(self) -> int:
        return int(self._n)

    def set_epoch(self, epoch: int):
        self._epoch = int(epoch)

    def __iter__(self) -> Iterator[np.ndarray]:
        if self._lib is not None:
            self._lib.dataio_sampler_epoch(
                self._sampler, self._epoch, 1 if self.shuffle else 0)
            while True:
                out = np.empty((self.batch_size, self.row_len), np.int32)
                got = self._lib.dataio_next_batch(
                    self._h, self._sampler, self.batch_size,
                    out.ctypes.data_as(ctypes.c_void_p))
                if got <= 0:
                    break
                if got < self.batch_size and self.drop_last:
                    break
                yield out[:got]
        else:
            order = np.arange(self._n)
            if self.shuffle:
                np.random.default_rng(
                    self.seed ^ (0x9E3779B9 * (self._epoch + 1))).shuffle(order)
            data = self._mm.reshape(self._n, self.row_len)
            for i in range(0, self._n, self.batch_size):
                idx = order[i:i + self.batch_size]
                if len(idx) < self.batch_size and self.drop_last:
                    break
                yield np.asarray(data[idx], np.int32)
        self._epoch += 1

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None:
            if getattr(self, "_sampler", None):
                lib.dataio_sampler_free(self._sampler)
            if getattr(self, "_h", None):
                lib.dataio_close(self._h)
