"""paddle.autograd parity: backward, grad, no_grad, PyLayer, jacobian/hessian.

Reference: paddle/fluid/eager/backward.cc (engine — implemented in tensor.py),
eager/pylayer (PyLayer), python/paddle/autograd/autograd.py (jacobian/hessian).
The functional jacobian/hessian are TPU-native: they delegate to jax.jacfwd /
jax.jacrev / jax.hessian over a functionalized view of the tape graph.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp

from .. import framework
from ..tensor import Tensor, apply_op, backward, grad, to_tensor

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "is_grad_enabled", "PyLayer", "PyLayerContext", "jacobian", "hessian",
    "vjp", "jvp",
]


class no_grad:
    """Context manager AND decorator (paddle.no_grad parity)."""

    def __enter__(self):
        self._cm = framework.no_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with framework.no_grad_guard():
                return fn(*args, **kwargs)
        return wrapper


class enable_grad:
    def __enter__(self):
        self._cm = framework.enable_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with framework.enable_grad_guard():
                return fn(*args, **kwargs)
        return wrapper


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    with framework._grad_mode(mode):
        yield


def is_grad_enabled():
    return framework.is_grad_enabled()


# ---------------------------------------------------------------------------
# PyLayer — custom forward/backward (eager/pylayer parity)
# ---------------------------------------------------------------------------


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        # capture the ACTIVE hooks at save time: backward usually runs
        # after the with-block exits, so unpack must use the same pair
        hooks = saved_tensors_hooks._active
        self._saved_hooks = hooks
        if hooks is not None:
            tensors = tuple(hooks[0](t) for t in tensors)
        self._saved = tensors

    @property
    def saved_tensor(self):
        hooks = getattr(self, "_saved_hooks", None)
        if hooks is not None:
            return tuple(hooks[1](t) for t in self._saved)
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """Subclass with static forward(ctx, ...) and backward(ctx, *grads)."""

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]

        with framework.no_grad_guard():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]

        if not framework.is_grad_enabled() or not any(
            not args[i].stop_gradient for i in tensor_pos
        ):
            return outs

        # Build a custom pullback that calls the user's backward.
        inputs = tuple(args[i] for i in tensor_pos)

        def pullback(cts):
            if not isinstance(cts, tuple):
                cts = (cts,)
            grads_in = cls.backward(ctx, *[Tensor(c) for c in cts])
            if not isinstance(grads_in, (tuple, list)):
                grads_in = (grads_in,)
            raw = []
            gi = iter(grads_in)
            for i in tensor_pos:
                g = next(gi, None)
                raw.append(None if g is None else (g._data if isinstance(g, Tensor) else jnp.asarray(g)))
            return tuple(raw)

        from ..tensor import TapeNode

        wrapped = [Tensor(o._data if isinstance(o, Tensor) else o, stop_gradient=False) for o in out_list]
        node = TapeNode(cls.__name__, pullback, inputs, tuple(wrapped))
        for idx, o in enumerate(wrapped):
            o._node = node
            o._out_idx = idx
        return tuple(wrapped) if multi else wrapped[0]

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Functional autodiff (python/paddle/autograd/autograd.py + incubate/autograd)
# ---------------------------------------------------------------------------


def _functionalize(func):
    """Wrap a Tensor->Tensor function as a raw jax function."""

    def raw_fn(*raws):
        outs = func(*[Tensor(r, stop_gradient=False) for r in raws])
        if isinstance(outs, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in outs)
        return outs._data if isinstance(outs, Tensor) else outs

    return raw_fn


def jacobian(ys, xs, batch_axis=None):
    """paddle.autograd.jacobian parity for the (func, inputs) functional form is
    jax.jacrev; the tensor form computes J of ys wrt xs via repeated backward."""
    if callable(ys):
        func, inputs = ys, xs
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        raw = _functionalize(func)
        jac = jax.jacrev(raw, argnums=tuple(range(len(inputs))))(*[t._data for t in inputs])
        if len(inputs) == 1:
            jac = jac[0]
            return Tensor(jac)
        return tuple(Tensor(j) for j in jac)
    # Tensor form: ys is output tensor, xs input tensor(s)
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    y_flat = ys.reshape([-1]) if ys.ndim else ys.reshape([1])
    rows = []
    n = y_flat.shape[0]
    for i in range(n):
        gs = grad([y_flat[i]], xs_list, retain_graph=True, allow_unused=True)
        rows.append([g._data.reshape(-1) if g is not None else jnp.zeros(int(jnp.prod(jnp.asarray(x.shape)))) for g, x in zip(gs, xs_list)])
    outs = []
    for j in range(len(xs_list)):
        outs.append(Tensor(jnp.stack([r[j] for r in rows])))
    return outs[0] if single else tuple(outs)


def hessian(func, inputs, batch_axis=None):
    if not callable(func):
        raise TypeError("hessian expects a callable")
    inputs_list = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    raw = _functionalize(func)
    h = jax.hessian(raw, argnums=tuple(range(len(inputs_list))))(*[t._data for t in inputs_list])
    if len(inputs_list) == 1:
        return Tensor(h[0][0] if isinstance(h, tuple) else h)
    return h


def vjp(func, xs, v=None):
    """paddle.incubate.autograd.vjp parity → jax.vjp."""
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    raw = _functionalize(func)
    out, pull = jax.vjp(raw, *[t._data for t in xs_list])
    if v is None:
        v_raw = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_raw = jax.tree_util.tree_map(lambda t: t._data if isinstance(t, Tensor) else t, v)
    grads = pull(v_raw)
    wrap = lambda o: jax.tree_util.tree_map(Tensor, o)
    return wrap(out), wrap(grads if len(xs_list) > 1 else grads[0])


def jvp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    raw = _functionalize(func)
    primals = [t._data for t in xs_list]
    if v is None:
        tangents = [jnp.ones_like(p) for p in primals]
    else:
        v_list = v if isinstance(v, (list, tuple)) else [v]
        tangents = [t._data if isinstance(t, Tensor) else t for t in v_list]
    out, tan = jax.jvp(raw, tuple(primals), tuple(tangents))
    wrap = lambda o: jax.tree_util.tree_map(Tensor, o)
    return wrap(out), wrap(tan)


class saved_tensors_hooks:
    """Reference autograd/saved_tensors_hooks: pack/unpack hooks over
    tensors saved for backward.  The tape saves residuals inside jax.vjp
    closures (opaque to python), so the hooks apply to the PyLayer save
    path: PyLayerContext.save_for_backward packs, saved_tensor unpacks."""

    _active = None

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        saved_tensors_hooks._active = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active = None
        return False


__all__ += ["saved_tensors_hooks"]
