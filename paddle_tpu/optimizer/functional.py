"""Functional optimizers — the compiled (pjit) training path.

Reference parity: optimizer/adamw.py:32 AdamW with multi_precision master
weights, plus the hybrid-parallel global-grad-norm clip
(fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:251).

TPU-native design: optimizer state is a pytree that shards exactly like the
params (ZeRO-1/2/3 fall out of sharding annotations on this state — SURVEY.md
§7 "ZeRO = sharded optimizer states annotations").  Update is a pure function,
so it lives inside the same jit as fwd/bwd and XLA fuses it into the gradient
reduction epilogue.  Master weights: params may be bf16, state keeps fp32
copies (the multi_precision story of the reference).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp


class AdamWState(NamedTuple):
    step: jnp.ndarray        # scalar int32
    m: Any                   # pytree like params, fp32
    v: Any                   # pytree like params, fp32
    master: Any              # fp32 param copies (None per-leaf when param is fp32)


@dataclasses.dataclass(frozen=True)
class AdamW:
    learning_rate: Any = 1e-3          # float or callable(step) -> float
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 0.01
    grad_clip_norm: Optional[float] = None   # global-norm clip (ClipGradByGlobalNorm)
    multi_precision: bool = True

    # -- state ------------------------------------------------------------
    def init(self, params) -> AdamWState:
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        if self.multi_precision:
            master = jax.tree.map(
                lambda p: p.astype(jnp.float32) if p.dtype != jnp.float32 else p,
                params)
        else:
            master = jax.tree.map(lambda p: p, params)
        return AdamWState(step=jnp.zeros((), jnp.int32), m=zeros,
                          v=jax.tree.map(jnp.copy, zeros), master=master)

    def _lr(self, step):
        lr = self.learning_rate
        return lr(step) if callable(lr) else lr

    # -- update -----------------------------------------------------------
    def update(self, grads, state: AdamWState, params):
        """Returns (new_params, new_state).  All math fp32 on master weights."""
        step = state.step + 1
        if self.grad_clip_norm is not None:
            gnorm = global_norm(grads)
            scale = jnp.minimum(1.0, self.grad_clip_norm / (gnorm + 1e-6))
            grads = jax.tree.map(lambda g: g * scale, grads)

        b1, b2 = self.beta1, self.beta2
        c1 = 1.0 - b1 ** step.astype(jnp.float32)
        c2 = 1.0 - b2 ** step.astype(jnp.float32)
        lr = self._lr(step)

        def upd(g, m, v, w):
            g = g.astype(jnp.float32)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * jnp.square(g)
            mh = m / c1
            vh = v / c2
            w = w - lr * (mh / (jnp.sqrt(vh) + self.epsilon) + self.weight_decay * w)
            return m, v, w

        flat_g, treedef = jax.tree.flatten(grads)
        flat_m = treedef.flatten_up_to(state.m)
        flat_v = treedef.flatten_up_to(state.v)
        flat_w = treedef.flatten_up_to(state.master)
        out = [upd(g, m, v, w) for g, m, v, w in zip(flat_g, flat_m, flat_v, flat_w)]
        new_m = treedef.unflatten([o[0] for o in out])
        new_v = treedef.unflatten([o[1] for o in out])
        new_master = treedef.unflatten([o[2] for o in out])

        flat_p = treedef.flatten_up_to(params)
        new_params = treedef.unflatten(
            [w.astype(p.dtype) for w, p in zip([o[2] for o in out], flat_p)])
        return new_params, AdamWState(step=step, m=new_m, v=new_v, master=new_master)


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


@dataclasses.dataclass(frozen=True)
class SGDM:
    """Functional SGD with momentum (reference optimizer/momentum.py analog)."""
    learning_rate: Any = 1e-2
    momentum: float = 0.9
    weight_decay: float = 0.0

    def init(self, params):
        return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def update(self, grads, state, params):
        lr = self.learning_rate
        lr = lr(None) if callable(lr) else lr

        def upd(g, s, p):
            g = g.astype(jnp.float32) + self.weight_decay * p.astype(jnp.float32)
            s = self.momentum * s + g
            return s, (p.astype(jnp.float32) - lr * s).astype(p.dtype)

        flat_g, treedef = jax.tree.flatten(grads)
        flat_s = treedef.flatten_up_to(state)
        flat_p = treedef.flatten_up_to(params)
        out = [upd(g, s, p) for g, s, p in zip(flat_g, flat_s, flat_p)]
        return (treedef.unflatten([o[1] for o in out]),
                treedef.unflatten([o[0] for o in out]))


def cosine_schedule(base_lr: float, warmup_steps: int, total_steps: int,
                    min_ratio: float = 0.1):
    """LRScheduler analog (optimizer/lr.py CosineAnnealingDecay + LinearWarmup)."""
    def lr(step):
        step = step.astype(jnp.float32) if hasattr(step, "astype") else float(step)
        warm = base_lr * step / max(warmup_steps, 1)
        prog = jnp.clip((step - warmup_steps) / max(total_steps - warmup_steps, 1), 0.0, 1.0)
        cos = base_lr * (min_ratio + (1 - min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * prog)))
        return jnp.where(step < warmup_steps, warm, cos)
    return lr
