"""Optimizers (python/paddle/optimizer/ parity: optimizer.py:91 base, adamw.py:32).

Design: each optimizer defines a *functional* per-parameter update rule
(`_update_raw`) over raw jax arrays + a state dict of accumulator arrays.  The
eager `step()` applies it in place (dygraph parity); the jit engine
(paddle_tpu.jit.TrainStep) calls the same rule inside a compiled, donated
train step — one rule, two execution modes, like the reference's shared phi
kernels between dygraph and static.

Master weights: with multi_precision=True (AMP O2 parity), a float32 copy is
kept in the state and the bf16/fp16 param is re-derived each step — the
reference's master-weight mechanic (optimizer.py _multi_precision logic).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..tensor import Parameter, Tensor
from . import lr as lr  # noqa: PLC0414
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adam", "AdamW", "AdamMax", "LBFGS",
           "RMSProp", "Adadelta", "Lamb", "lr", "LRScheduler"]



def _updatable(p):
    """Reference optimizers update ANY tensor with stop_gradient=False, not
    just Parameters (optimizer.py accepts plain tensors in `parameters`) —
    filtering to Parameter silently no-ops user code like
    `SGD(parameters=[paddle.to_tensor(w, stop_gradient=False)])`."""
    if isinstance(p, Parameter):
        return p.trainable
    return isinstance(p, Tensor) and not p.stop_gradient


class Optimizer:
    _accum_names: tuple = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._step_count = 0
        # state: param-id -> {accum_name: raw array}
        self._state: dict[int, dict] = {}

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value

    @property
    def _learning_rate(self):
        return self._lr

    # -- state -------------------------------------------------------------
    def _init_param_state(self, p: Parameter) -> dict:
        state = {}
        raw = p._data
        needs_master = self._multi_precision and raw.dtype in (jnp.float16, jnp.bfloat16)
        if needs_master:
            state["master_weight"] = raw.astype(jnp.float32)
        for name in self._accum_names:
            state[name] = jnp.zeros_like(state.get("master_weight", raw))
        return state

    def _get_state(self, p: Parameter) -> dict:
        s = self._state.get(id(p))
        if s is None:
            s = self._init_param_state(p)
            self._state[id(p)] = s
        return s

    # -- update rule (override) ---------------------------------------------
    def _update_raw(self, param, grad, state, lr, step):
        """param/grad: raw float arrays (master precision); state: dict of raw
        arrays; returns (new_param, new_state)."""
        raise NotImplementedError

    # -- regularization -----------------------------------------------------
    def _wd_coeff(self):
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if isinstance(wd, (int, float)):
            return float(wd)
        coeff = getattr(wd, "_coeff", None)  # L2Decay object parity
        return float(coeff) if coeff is not None else 0.0

    def _l2_into_grad(self) -> bool:
        # classic L2 regularization (grad += wd * param); AdamW overrides to use
        # decoupled decay instead.
        return True

    def _live_params_and_grads(self):
        """Updatable params + their (possibly clipped) raw grads.  Shared by
        every eager step() so parameter-eligibility / clipping changes land
        in ONE place."""
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters; pass parameters=")
        params = [p for p in self._parameter_list if _updatable(p)]
        grads = [p.grad._data if p.grad is not None else None for p in params]
        if self._grad_clip is not None:
            live = [g for g in grads if g is not None]
            clipped = self._grad_clip.clip_raw(live)
            it = iter(clipped)
            grads = [next(it) if g is not None else None for g in grads]
        return params, grads

    # -- eager step ---------------------------------------------------------
    @jax.named_scope("optimizer_step")
    def step(self):
        params, grads = self._live_params_and_grads()
        lr_val = self.get_lr()
        wd = self._wd_coeff()
        self._step_count += 1
        for p, g in zip(params, grads):
            if g is None:
                continue
            state = self._get_state(p)
            master = state.get("master_weight")
            w = master if master is not None else p._data
            g = g.astype(w.dtype)
            if wd and self._l2_into_grad() and getattr(p, "regularizer", None) is None:
                g = g + wd * w
            p_lr = lr_val * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            new_w, new_state = self._update_raw(w, g, state, p_lr, self._step_count)
            if master is not None:
                new_state["master_weight"] = new_w
                p._data = new_w.astype(p._data.dtype)
            else:
                p._data = new_w
            self._state[id(p)] = new_state

    def clear_grad(self, set_to_zero=False):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- checkpoint ----------------------------------------------------------
    def state_dict(self):
        out = {"_step_count": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                s = self._state.get(id(p))
                if s is None:
                    continue
                for k, v in s.items():
                    out[f"{p.name}_{k}"] = Tensor(v)
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("_step_count", 0))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state:
            self._lr.set_state_dict(state["LR_Scheduler"])
        if self._parameter_list:
            for p in self._parameter_list:
                s = {}
                for name in self._accum_names + ("master_weight",):
                    k = f"{p.name}_{name}"
                    if k in state:
                        v = state[k]
                        s[name] = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if s:
                    self._state[id(p)] = s

    # -- functional API for the jit engine ----------------------------------
    def functional_init(self, raw_params: list):
        """Build accumulator state for a flat list of raw params."""
        states = []
        for raw in raw_params:
            s = {}
            needs_master = self._multi_precision and raw.dtype in (jnp.float16, jnp.bfloat16)
            if needs_master:
                s["master_weight"] = raw.astype(jnp.float32)
            for name in self._accum_names:
                s[name] = jnp.zeros_like(s.get("master_weight", raw))
            states.append(s)
        return {"step": jnp.zeros((), jnp.int32), "param_states": states}

    def functional_apply(self, raw_params: list, raw_grads: list, opt_state, lr=None):
        """Pure update: returns (new_params, new_state).  Called under jit."""
        step = opt_state["step"] + 1
        lr_val = self.get_lr() if lr is None else lr
        wd = self._wd_coeff()
        if self._grad_clip is not None:
            raw_grads = self._grad_clip.clip_raw(raw_grads)
        new_params, new_states = [], []
        for w0, g, s in zip(raw_params, raw_grads, opt_state["param_states"]):
            if g is None:
                new_params.append(w0)
                new_states.append(s)
                continue
            master = s.get("master_weight")
            w = master if master is not None else w0
            g = g.astype(w.dtype)
            if wd and self._l2_into_grad():
                g = g + wd * w
            new_w, new_s = self._update_raw(w, g, s, lr_val, step)
            if master is not None:
                new_s["master_weight"] = new_w
                new_params.append(new_w.astype(w0.dtype))
            else:
                new_params.append(new_w)
            new_states.append(new_s)
        return new_params, {"step": step, "param_states": new_states}

    # paddle API compat
    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from .. import framework as _fw

        cap = _fw.get_state().capture_program
        if cap is not None:
            # static-graph mode: register the train target; Executor.run
            # computes grads by jax.grad over the replayed program
            cap._mark_train(self, loss)
            return None, None
        loss.backward()
        self.step()
        return None, None

    def _apply_optimize(self, loss, startup_program, params_grads):
        self.step()


class SGD(Optimizer):
    def _update_raw(self, w, g, s, lr, step):
        return w - lr * g, s


class Momentum(Optimizer):
    _accum_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update_raw(self, w, g, s, lr, step):
        v = self._momentum * s["velocity"] + g
        if self._nesterov:
            new_w = w - lr * (g + self._momentum * v)
        else:
            new_w = w - lr * v
        return new_w, {**s, "velocity": v}


class Adagrad(Optimizer):
    _accum_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_raw(self, w, g, s, lr, step):
        m = s["moment"] + jnp.square(g)
        return w - lr * g / (jnp.sqrt(m) + self._epsilon), {**s, "moment": m}


class Adam(Optimizer):
    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update_raw(self, w, g, s, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * s["moment1"] + (1 - b1) * g
        v = b2 * s["moment2"] + (1 - b2) * jnp.square(g)
        step_f = jnp.asarray(step, dtype=w.dtype) if not isinstance(step, int) else step
        bc1 = 1 - b1**step_f if isinstance(step, int) else 1 - jnp.power(jnp.asarray(b1, w.dtype), step_f)
        bc2 = 1 - b2**step_f if isinstance(step, int) else 1 - jnp.power(jnp.asarray(b2, w.dtype), step_f)
        m_hat = m / bc1
        v_hat = v / bc2
        new_w = w - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        return new_w, {**s, "moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py:32)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name=name)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _l2_into_grad(self):
        return False

    def _update_raw(self, w, g, s, lr, step, decay=True):
        if decay:
            w = w * (1.0 - lr * self._wd_coeff())
        return super()._update_raw(w, g, s, lr, step)

    def step(self):
        # same as base but honoring apply_decay_param_fun per param
        params, grads = self._live_params_and_grads()
        lr_val = self.get_lr()
        self._step_count += 1
        for p, g in zip(params, grads):
            if g is None:
                continue
            state = self._get_state(p)
            master = state.get("master_weight")
            w = master if master is not None else p._data
            g = g.astype(w.dtype)
            decay = True
            if self._apply_decay_param_fun is not None:
                decay = self._apply_decay_param_fun(p.name)
            p_lr = lr_val * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            if self._lr_ratio is not None:
                p_lr = p_lr * self._lr_ratio(p)
            new_w, new_state = self._update_raw(w, g, state, p_lr, self._step_count, decay=decay)
            if master is not None:
                new_state["master_weight"] = new_w
                p._data = new_w.astype(p._data.dtype)
            else:
                p._data = new_w
            self._state[id(p)] = new_state


class AdamMax(Optimizer):
    _accum_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_raw(self, w, g, s, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * s["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * s["inf_norm"], jnp.abs(g))
        step_f = step if isinstance(step, int) else jnp.asarray(step, w.dtype)
        bc1 = 1 - b1**step_f if isinstance(step, int) else 1 - jnp.power(jnp.asarray(b1, w.dtype), step_f)
        new_w = w - lr / bc1 * m / (u + self._epsilon)
        return new_w, {**s, "moment": m, "inf_norm": u}


Adamax = AdamMax


class RMSProp(Optimizer):
    _accum_names = ("mean_square", "mean_grad", "momentum_acc")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _update_raw(self, w, g, s, lr, step):
        ms = self._rho * s["mean_square"] + (1 - self._rho) * jnp.square(g)
        if self._centered:
            mg = self._rho * s["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            mg = s["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * s["momentum_acc"] + lr * g / denom
        return w - mom, {**s, "mean_square": ms, "mean_grad": mg, "momentum_acc": mom}


class Adadelta(Optimizer):
    _accum_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._epsilon, self._rho = epsilon, rho

    def _update_raw(self, w, g, s, lr, step):
        asg = self._rho * s["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        update = jnp.sqrt(s["avg_squared_update"] + self._epsilon) / jnp.sqrt(asg + self._epsilon) * g
        asu = self._rho * s["avg_squared_update"] + (1 - self._rho) * jnp.square(update)
        return w - lr * update, {**s, "avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_raw(self, w, g, s, lr, step, decay=True):
        b1, b2 = self._beta1, self._beta2
        m = b1 * s["moment1"] + (1 - b1) * g
        v = b2 * s["moment2"] + (1 - b2) * jnp.square(g)
        step_f = step if isinstance(step, int) else jnp.asarray(step, w.dtype)
        bc1 = 1 - b1**step_f if isinstance(step, int) else 1 - jnp.power(jnp.asarray(b1, w.dtype), step_f)
        bc2 = 1 - b2**step_f if isinstance(step, int) else 1 - jnp.power(jnp.asarray(b2, w.dtype), step_f)
        r = (m / bc1) / (jnp.sqrt(v / bc2) + self._epsilon)
        if decay:
            r = r + self._lamb_wd * w
        w_norm = jnp.linalg.norm(w)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return w - lr * trust * r, {**s, "moment1": m, "moment2": v}

    def step(self):
        params, grads = self._live_params_and_grads()
        lr_val = self.get_lr()
        self._step_count += 1
        for p, g in zip(params, grads):
            if g is None:
                continue
            state = self._get_state(p)
            master = state.get("master_weight")
            w = master if master is not None else p._data
            g = g.astype(w.dtype)
            decay = True
            if self._exclude_fn is not None:
                decay = not self._exclude_fn(p.name)
            new_w, new_state = self._update_raw(w, g, state, lr_val, self._step_count, decay=decay)
            if master is not None:
                new_state["master_weight"] = new_w
                p._data = new_w.astype(p._data.dtype)
            else:
                p._data = new_w
            self._state[id(p)] = new_state


from .lbfgs import LBFGS  # noqa: E402,F401
