"""L-BFGS optimizer (reference: python/paddle/optimizer/lbfgs.py:1).

Classic limited-memory BFGS with the two-loop recursion over a flattened
parameter vector, optional strong-Wolfe line search, closure-based step()
(the closure re-evaluates loss + grads, like the reference's).  Eager-only
by nature — each iteration re-runs the user's forward/backward.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import Optimizer
from . import _updatable

__all__ = ["LBFGS"]


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2); standard
    safeguarded formula (Nocedal & Wright eq. 3.59)."""
    if bounds is not None:
        lo, hi = bounds
    else:
        lo, hi = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_sq = d1 ** 2 - g1 * g2
    if d2_sq >= 0:
        d2 = d2_sq ** 0.5
        if x1 <= x2:
            xm = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            xm = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(xm, lo), hi)
    return (lo + hi) / 2.0


class LBFGS(Optimizer):
    """Reference optimizer/lbfgs.py — step(closure) minimizes the closure."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self._max_iter = max_iter
        self._max_eval = max_eval if max_eval is not None \
            else max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        self._line_search_fn = line_search_fn
        self._s_hist: list = []
        self._y_hist: list = []
        self._rho: list = []
        self._prev_grad = None
        self._n_evals = 0

    # -- flat views ---------------------------------------------------------
    def _params(self):
        ps = [p for p in (self._parameter_list or []) if _updatable(p)]
        if not ps:
            raise ValueError("LBFGS requires parameters=")
        return ps

    def _flat_grad(self, params):
        gs = []
        for p in params:
            g = p.grad._data if p.grad is not None \
                else jnp.zeros_like(p._data)
            if self._wd_coeff():
                g = g + self._wd_coeff() * p._data
            gs.append(g.astype(jnp.float32).reshape(-1))
        return jnp.concatenate(gs)

    def _flat_params(self, params):
        return jnp.concatenate(
            [p._data.astype(jnp.float32).reshape(-1) for p in params])

    def _assign(self, params, flat):
        off = 0
        for p in params:
            n = int(jnp.prod(jnp.asarray(p._data.shape))) if p._data.ndim \
                else 1
            p._data = flat[off:off + n].reshape(p._data.shape).astype(
                p._data.dtype)
            off += n

    # -- direction ----------------------------------------------------------
    def _two_loop(self, grad):
        q = grad
        alphas = []
        for s, y, rho in zip(reversed(self._s_hist), reversed(self._y_hist),
                             reversed(self._rho)):
            a = rho * jnp.vdot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._y_hist:
            y, s = self._y_hist[-1], self._s_hist[-1]
            gamma = jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-10)
            q = q * gamma
        for (s, y, rho), a in zip(
                zip(self._s_hist, self._y_hist, self._rho),
                reversed(alphas)):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return -q

    # -- line search --------------------------------------------------------
    def _eval(self, closure, params, x, t, d):
        self._assign(params, x + t * d)
        loss = closure()
        self._n_evals += 1
        g = self._flat_grad(params)
        return float(loss.numpy() if hasattr(loss, "numpy") else loss), g

    def _strong_wolfe(self, closure, params, x, t, d, f0, g0,
                      c1=1e-4, c2=0.9, max_ls=25):
        gtd0 = float(jnp.vdot(g0, d))
        f_prev, t_prev, g_prev = f0, 0.0, g0
        for ls in range(max_ls):
            f_new, g_new = self._eval(closure, params, x, t, d)
            gtd = float(jnp.vdot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or (ls > 0 and f_new >= f_prev):
                return self._zoom(closure, params, x, d, f0, gtd0,
                                  t_prev, f_prev, g_prev, t, f_new, g_new,
                                  c1, c2)
            if abs(gtd) <= -c2 * gtd0:
                return t, f_new, g_new
            if gtd >= 0:
                return self._zoom(closure, params, x, d, f0, gtd0,
                                  t, f_new, g_new, t_prev, f_prev, g_prev,
                                  c1, c2)
            t_prev, f_prev, g_prev = t, f_new, g_new
            t = min(2 * t, 10.0)
        return t, f_new, g_new

    def _zoom(self, closure, params, x, d, f0, gtd0, t_lo, f_lo, g_lo,
              t_hi, f_hi, g_hi, c1, c2, max_zoom=10):
        for _ in range(max_zoom):
            if abs(t_hi - t_lo) < 1e-9:
                break
            t = _cubic_interpolate(
                t_lo, f_lo, float(jnp.vdot(g_lo, d)),
                t_hi, f_hi, float(jnp.vdot(g_hi, d)))
            f_new, g_new = self._eval(closure, params, x, t, d)
            gtd = float(jnp.vdot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
                t_hi, f_hi, g_hi = t, f_new, g_new
            else:
                if abs(gtd) <= -c2 * gtd0:
                    return t, f_new, g_new
                if gtd * (t_hi - t_lo) >= 0:
                    t_hi, f_hi, g_hi = t_lo, f_lo, g_lo
                t_lo, f_lo, g_lo = t, f_new, g_new
        # params may sit at the last trial point — put them at the returned
        # one so loss/grad/history stay consistent (torch's final _add_grad)
        self._assign(params, x + t_lo * d)
        return t_lo, f_lo, g_lo

    # -- step ---------------------------------------------------------------
    def step(self, closure=None):
        """Run up to max_iter L-BFGS iterations; `closure` re-evaluates the
        loss (clearing and re-accumulating grads) and returns it."""
        if closure is None:
            raise ValueError(
                "LBFGS.step requires a closure that re-evaluates the loss")
        params = self._params()
        orig_loss = closure()
        self._n_evals = 1
        loss = float(orig_loss.numpy()
                     if hasattr(orig_loss, "numpy") else orig_loss)
        grad = self._flat_grad(params)
        if float(jnp.abs(grad).max()) <= self._tol_grad:
            return orig_loss
        lr = self.get_lr()

        for it in range(self._max_iter):
            d = -grad if not self._y_hist else self._two_loop(grad)
            x = self._flat_params(params)
            gtd = float(jnp.vdot(grad, d))
            if gtd > -self._tol_change:
                break
            # first iteration: scale like the reference/torch
            t = min(1.0, 1.0 / float(jnp.abs(grad).sum())) * lr if it == 0 \
                and not self._y_hist else lr

            if self._line_search_fn == "strong_wolfe":
                t, f_new, g_new = self._strong_wolfe(
                    closure, params, x, t, d, loss, grad)
            else:
                f_new, g_new = self._eval(closure, params, x, t, d)

            s = (self._flat_params(params) - x)
            y = g_new - grad
            ys = float(jnp.vdot(y, s))
            if ys > 1e-10:
                if len(self._s_hist) >= self._history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
                    self._rho.pop(0)
                self._s_hist.append(s)
                self._y_hist.append(y)
                self._rho.append(1.0 / ys)

            grad_change = float(jnp.abs(g_new).max())
            step_change = float(jnp.abs(s).max())
            loss_change = abs(f_new - loss)
            loss, grad = f_new, g_new
            if (grad_change <= self._tol_grad
                    or step_change <= self._tol_change
                    or loss_change < self._tol_change
                    or self._n_evals >= self._max_eval):
                break
        self._step_count += 1
        return orig_loss
