"""paddle.audio — DSP feature domain library (SURVEY C48; reference
python/paddle/audio/)."""

from . import functional  # noqa: F401
from . import features  # noqa: F401
