"""paddle.audio — DSP feature domain library (SURVEY C48; reference
python/paddle/audio/)."""

from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import info, load, save  # noqa: F401
