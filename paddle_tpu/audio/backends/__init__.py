"""paddle.audio.backends — audio IO (reference
python/paddle/audio/backends/{wave_backend.py:37,89,168,init_backend.py:37}).

The built-in backend is the stdlib-`wave` PCM16 backend, exactly like the
reference's default; `set_backend` accepts any registered backend module
exposing info/load/save (the reference's paddleaudio hook becomes a plain
registration here — no version sniffing needed)."""

from __future__ import annotations

import wave as _wave
from dataclasses import dataclass
from typing import List, Tuple, Union

import numpy as np

from ...tensor import Tensor, to_tensor

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend", "register_backend"]


@dataclass
class AudioInfo:
    """(reference backends/backend.py AudioInfo)"""
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str


class _WaveBackend:
    """PCM16 WAV via the stdlib wave module (wave_backend.py)."""

    name = "wave_backend"

    @staticmethod
    def info(filepath) -> AudioInfo:
        with _wave.open(str(filepath), "rb") as f:
            return AudioInfo(sample_rate=f.getframerate(),
                             num_samples=f.getnframes(),
                             num_channels=f.getnchannels(),
                             bits_per_sample=8 * f.getsampwidth(),
                             encoding="PCM_S")

    @staticmethod
    def load(filepath, frame_offset: int = 0, num_frames: int = -1,
             normalize: bool = True, channels_first: bool = True
             ) -> Tuple[Tensor, int]:
        file_obj = filepath if hasattr(filepath, "read") else open(
            str(filepath), "rb")
        try:
            f = _wave.open(file_obj)
        except _wave.Error as e:
            file_obj.close()
            raise NotImplementedError(
                f"only PCM16 WAV is supported by the wave backend ({e}); "
                "register a richer backend via "
                "paddle.audio.backends.register_backend") from e
        if f.getsampwidth() != 2:
            width = f.getsampwidth()
            file_obj.close()
            raise NotImplementedError(
                f"only PCM16 WAV is supported by the wave backend "
                f"(got sample width {width} bytes); register a richer "
                "backend via paddle.audio.backends.register_backend")
        channels = f.getnchannels()
        sr = f.getframerate()
        frames = f.getnframes()
        content = f.readframes(frames)
        file_obj.close()
        arr = np.frombuffer(content, dtype=np.int16)
        if normalize:
            arr = arr.astype(np.float32) / 2.0 ** 15
        wavef = arr.reshape(frames, channels)
        if num_frames != -1:
            wavef = wavef[frame_offset:frame_offset + num_frames, :]
        elif frame_offset:
            wavef = wavef[frame_offset:, :]
        # normalize=False returns native int16 PCM (reference contract)
        t = to_tensor(wavef)
        if channels_first:
            from ... import ops
            t = ops.transpose(t, [1, 0])
        return t, sr

    @staticmethod
    def save(filepath, src: Tensor, sample_rate: int,
             channels_first: bool = True, encoding: str = "PCM_16",
             bits_per_sample: int = 16):
        if bits_per_sample != 16 or encoding != "PCM_16":
            raise ValueError("wave backend writes PCM_16 only")
        arr = np.asarray(src.numpy() if isinstance(src, Tensor) else src)
        if arr.ndim == 1:
            arr = arr[None, :]
        if not channels_first:
            arr = arr.T
        if np.issubdtype(arr.dtype, np.floating):
            arr = np.clip(arr, -1.0, 1.0)
            arr = (arr * (2 ** 15 - 1)).astype(np.int16)
        with _wave.open(str(filepath), "wb") as f:
            f.setnchannels(arr.shape[0])
            f.setsampwidth(2)
            f.setframerate(int(sample_rate))
            f.writeframes(arr.T.reshape(-1).tobytes())


_BACKENDS = {"wave_backend": _WaveBackend}
_CURRENT = "wave_backend"


def register_backend(name: str, backend) -> None:
    """Register a backend object exposing info/load/save."""
    _BACKENDS[name] = backend


def list_available_backends() -> List[str]:
    return sorted(_BACKENDS)


def get_current_backend() -> str:
    return _CURRENT


def set_backend(backend_name: str):
    global _CURRENT
    if backend_name not in _BACKENDS:
        raise NotImplementedError(
            f"backend {backend_name!r} is not registered; available: "
            f"{list_available_backends()}")
    _CURRENT = backend_name


def info(filepath) -> AudioInfo:
    return _BACKENDS[_CURRENT].info(filepath)


def load(filepath, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    return _BACKENDS[_CURRENT].load(filepath, frame_offset, num_frames,
                                    normalize, channels_first)


def save(filepath, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16):
    return _BACKENDS[_CURRENT].save(filepath, src, sample_rate,
                                    channels_first, encoding,
                                    bits_per_sample)
