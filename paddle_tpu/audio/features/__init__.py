"""paddle.audio.features — Spectrogram / MelSpectrogram / LogMel / MFCC
layers (reference python/paddle/audio/features/layers.py:24,106,206,309).

TPU-native: framing is one strided gather, the DFT is a (win, 2F) matmul
against a precomputed real/imag basis, mel and DCT are further matmuls —
the whole feature stack is MXU-friendly and jit/grad-safe with NO complex
intermediates (some TPU plugins have no complex-dtype support at all, so
a `signal.stft`-based path would not differentiate on-device).  Parity with
`paddle_tpu.signal.stft` — which the reference's features call
(python/paddle/audio/features/layers.py:100) — is pinned by
tests/test_fft_signal.py::TestSpectrogramStftParity.
"""

from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ...nn.layer import Layer
from ...tensor import Tensor, to_tensor
from ..functional import (compute_fbank_matrix, create_dct, get_window,
                          power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length, hop_length, center, pad_mode):
    if center:
        pad = frame_length // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    n = 1 + (x.shape[-1] - frame_length) // hop_length
    idx = (jnp.arange(n)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return x[..., idx]  # (..., n_frames, frame_length)


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        win = get_window(window, self.win_length, dtype=dtype)._data
        if self.win_length < n_fft:  # center-pad the window to n_fft
            lpad = (n_fft - self.win_length) // 2
            win = jnp.pad(win, (lpad, n_fft - self.win_length - lpad))
        self._win = win
        k = np.arange(1 + n_fft // 2)[:, None]
        t = np.arange(n_fft)[None, :]
        ang = -2 * np.pi * k * t / n_fft
        self._cos = jnp.asarray(np.cos(ang).T, jnp.float32)  # (n_fft, F)
        self._sin = jnp.asarray(np.sin(ang).T, jnp.float32)

    def forward(self, x):
        from ...tensor import apply_op
        xt = x if isinstance(x, Tensor) else to_tensor(x)

        def f(raw):
            frames = _frame(raw.astype(jnp.float32), self.n_fft, self.hop,
                            self.center, self.pad_mode)
            frames = frames * self._win
            re = frames @ self._cos
            im = frames @ self._sin
            mag2 = re * re + im * im        # (..., n_frames, F)
            spec = jnp.power(jnp.maximum(mag2, 1e-30), self.power / 2.0)
            return jnp.swapaxes(spec, -1, -2)  # (..., F, n_frames)

        return apply_op("spectrogram", f, xt)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.fbank_matrix = compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        from ...tensor import apply_op
        spec = self._spectrogram(x)
        return apply_op("mel_fbank",
                        lambda s: self.fbank_matrix._data @ s, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 **mel_kwargs):
        super().__init__()
        self._melspectrogram = MelSpectrogram(sr=sr, **mel_kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                           top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_mels: int = 64,
                 **logmel_kwargs):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(sr=sr, n_mels=n_mels,
                                                     **logmel_kwargs)
        self.dct_matrix = create_dct(n_mfcc=n_mfcc, n_mels=n_mels)

    def forward(self, x):
        from ...tensor import apply_op
        logmel = self._log_melspectrogram(x)
        return apply_op(
            "mfcc_dct",
            lambda lm: jnp.swapaxes(
                jnp.swapaxes(lm, -1, -2) @ self.dct_matrix._data, -1, -2),
            logmel)
