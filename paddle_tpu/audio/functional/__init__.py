"""paddle.audio.functional — mel/DCT/window DSP primitives (SURVEY C48).

Reference: python/paddle/audio/functional/{functional.py,window.py}.
TPU-native: everything is jnp (STFT frames batch into one big matmul with
the DFT/mel bases — MXU work, not a CPU resampler in the loop).
"""

from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ...tensor import Tensor, to_tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def hz_to_mel(freq, htk: bool = False):
    """Reference audio/functional/functional.py:22 (slaney default)."""
    scalar = isinstance(freq, (int, float))
    f = jnp.asarray(freq, jnp.float32) if scalar else _raw(freq)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                              / min_log_hz) / logstep,
                        mels)
    return float(out) if scalar else to_tensor(out)


def mel_to_hz(mel, htk: bool = False):
    scalar = isinstance(mel, (int, float))
    m = jnp.asarray(mel, jnp.float32) if scalar else _raw(mel)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(m >= min_log_mel,
                        min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                        freqs)
    return float(out) if scalar else to_tensor(out)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float32"):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return to_tensor(_raw(mel_to_hz(to_tensor(mels), htk)).astype(dtype))


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32"):
    return to_tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype: str = "float32"):
    """(n_mels, 1 + n_fft//2) triangular mel filterbank
    (functional.py:186)."""
    if f_max is None:
        f_max = sr / 2.0
    fft_f = _raw(fft_frequencies(sr, n_fft))
    mel_f = _raw(mel_frequencies(n_mels + 2, f_min, f_max, htk))
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        weights = weights / jnp.maximum(
            1e-10, jnp.linalg.norm(weights, ord=norm, axis=-1, keepdims=True))
    return to_tensor(weights.astype(dtype))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """10*log10(S/ref) with clamp (functional.py:259)."""
    if top_db is not None and top_db < 0:
        raise ValueError("top_db must be non-negative")
    from ...tensor import Tensor, apply_op
    xt = spect if isinstance(spect, Tensor) else to_tensor(_raw(spect))

    def f(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return apply_op("power_to_db", f, xt)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float32"):
    """(n_mels, n_mfcc) DCT-II basis (functional.py:303)."""
    n = jnp.arange(n_mels, dtype=jnp.float64)
    k = jnp.arange(n_mfcc, dtype=jnp.float64)[:, None]
    dct = jnp.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm is None:
        dct = dct * 2.0
    elif norm == "ortho":
        dct = dct * jnp.sqrt(2.0 / n_mels)
        dct = dct.at[0].multiply(1.0 / jnp.sqrt(2.0))
    else:
        raise ValueError(f"unsupported norm {norm}")
    return to_tensor(dct.T.astype(dtype))


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype: str = "float32"):
    """hann/hamming/blackman/bartlett/kaiser/gaussian/taylor subset of
    window.py:335."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = np.arange(win_length)
    L = win_length if fftbins else win_length - 1
    if name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * np.pi * n / L)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * n / L)
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * n / L)
             + 0.08 * np.cos(4 * np.pi * n / L))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * n / L - 1.0)
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        w = np.i0(beta * np.sqrt(np.clip(
            1 - (2 * n / L - 1.0) ** 2, 0, None))) / np.i0(beta)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((n - L / 2.0) / std) ** 2)
    else:
        raise ValueError(f"unsupported window {name}")
    return to_tensor(jnp.asarray(w, dtype=jnp.dtype(dtype)))
