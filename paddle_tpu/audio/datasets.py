"""paddle.audio.datasets — ESC50 / TESS (reference python/paddle/audio/
datasets/{esc50.py,tess.py}).

The reference downloads the corpora; this environment has zero egress, so
both datasets are FILE-BASED first (`archive` points at the extracted
corpus directory) with a deterministic synthetic fallback sized like the
real splits.  Items match the reference: (waveform float32 (n,), label
int64); feat_type='raw' only (spectrogram features come from
paddle.audio.features on the returned waveform).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..io import Dataset

__all__ = ["ESC50", "TESS"]


def _synth_wave(rng, sr, seconds, f0):
    t = np.arange(int(sr * seconds), dtype=np.float32) / sr
    return (0.5 * np.sin(2 * np.pi * f0 * t)
            + 0.05 * rng.standard_normal(t.size)).astype(np.float32)


class ESC50(Dataset):
    """ESC-50 environmental sounds, 50 classes, 5 folds (reference
    esc50.py:151).  mode='train' keeps folds != split; 'dev' keeps == split.
    archive: directory of .wav files named fold-*-*-target.wav (the ESC
    naming) — None -> synthetic tones, 2 clips per class."""

    n_classes = 50
    sample_rate = 44100

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", archive: Optional[str] = None,
                 n_synthetic_per_class: int = 2, **kwargs):
        if split not in range(1, 6):
            raise ValueError(f"split must be in [1, 5], got {split}")
        if feat_type != "raw":
            raise ValueError(
                "feat_type='raw' only; build spectrograms with "
                "paddle.audio.features over the raw waveform")
        self.mode = mode
        items: List[Tuple[np.ndarray, int, int]] = []  # (wave, fold, label)
        if archive is None:
            rng = np.random.default_rng(50)
            for label in range(self.n_classes):
                for j in range(n_synthetic_per_class):
                    fold = (label + j) % 5 + 1
                    w = _synth_wave(rng, self.sample_rate, 0.005,
                                    100.0 + 17.0 * label)
                    items.append((w, fold, label))
        else:
            from . import backends
            for name in sorted(os.listdir(archive)):
                if not name.endswith(".wav"):
                    continue
                parts = name[:-4].split("-")
                fold, label = int(parts[0]), int(parts[-1])
                w, _ = backends.load(os.path.join(archive, name))
                items.append((np.asarray(w.numpy()).reshape(-1), fold,
                              label))
        keep = (lambda f: f != split) if mode == "train" \
            else (lambda f: f == split)
        self._items = [(w, lab) for w, f, lab in items if keep(f)]

    def __getitem__(self, idx):
        w, lab = self._items[idx]
        return w, np.int64(lab)

    def __len__(self):
        return len(self._items)


class TESS(Dataset):
    """Toronto emotional speech set, 7 emotions (reference tess.py:140).
    n_folds cross-validation over speakers; archive: directory of
    <word>_<emotion>.wav files — None -> synthetic."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]
    sample_rate = 24414

    def __init__(self, mode: str = "train", n_folds: int = 5, split: int = 1,
                 feat_type: str = "raw", archive: Optional[str] = None,
                 n_synthetic_per_class: int = 5, **kwargs):
        if split not in range(1, n_folds + 1):
            raise ValueError(f"split must be in [1, {n_folds}]")
        if feat_type != "raw":
            raise ValueError(
                "feat_type='raw' only; build spectrograms with "
                "paddle.audio.features over the raw waveform")
        self.mode = mode
        items: List[Tuple[np.ndarray, int]] = []
        if archive is None:
            rng = np.random.default_rng(7)
            for lab, emo in enumerate(self.EMOTIONS):
                for _ in range(n_synthetic_per_class):
                    items.append((_synth_wave(rng, self.sample_rate, 0.005,
                                              150.0 + 40.0 * lab), lab))
        else:
            from . import backends
            for name in sorted(os.listdir(archive)):
                if not name.endswith(".wav"):
                    continue
                emo = name[:-4].split("_")[-1].lower()
                if emo not in self.EMOTIONS:
                    continue
                w, _ = backends.load(os.path.join(archive, name))
                items.append((np.asarray(w.numpy()).reshape(-1),
                              self.EMOTIONS.index(emo)))
        fold_of = lambda i: i % n_folds + 1  # noqa: E731
        keep = (lambda f: f != split) if mode == "train" \
            else (lambda f: f == split)
        self._items = [(w, lab) for i, (w, lab) in enumerate(items)
                       if keep(fold_of(i))]

    def __getitem__(self, idx):
        w, lab = self._items[idx]
        return w, np.int64(lab)

    def __len__(self):
        return len(self._items)
