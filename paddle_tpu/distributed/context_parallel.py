"""Context / sequence parallelism: ring attention + Ulysses (the `sep` axis).

The reference RESERVED a `sep` topology axis (fleet/base/topology.py:63,183 and
the fused dp-sep group at topology.py:237) but shipped no layer that consumes
it — only Megatron TP-SP (fleet/utils/sequence_parallel_utils.py) exists there
(SURVEY.md §5 "Long-context").  This module implements the missing capability
TPU-natively:

  * **Ring attention** — q stays put, k/v chunks rotate around the `sep` ring
    via `jax.lax.ppermute` (ICI neighbor exchange); partial attention outputs
    merge with the online-softmax (max/sum-rescale) rule, so the full (S,S)
    score matrix never exists and sequence length scales linearly with the
    number of chips.  (Liu et al., Ring Attention with Blockwise Transformers.)
  * **Ulysses** — all-to-all swaps the sharded axis from sequence to heads
    (`jax.lax.all_to_all` over `sep`), runs ordinary flash attention on the
    full sequence for H/n heads, and swaps back.  (DeepSpeed-Ulysses.)

Both are written as *local* functions over a named axis (usable inside any
`shard_map`) plus a global wrapper that installs the shard_map over the
standard mesh (batch over data×sharding, seq over sep, heads over model).
AD works through both: the transpose of `ppermute` is the reverse permute and
the transpose of `all_to_all` is `all_to_all`, so `jax.grad` of the wrapper is
itself a ring/all-to-all program — no custom VJP needed.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from ._shard_map_compat import shard_map, typeof
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib

_NEG_INF = np.float32(-1e30)
_TINY = np.float32(1e-30)


def _pvary_like(val, ref):
    """Cast `val` to carry the same varying-manual-axes (vma) type as `ref` —
    needed for scan carries created fresh inside (nested) shard_map bodies."""
    want = getattr(typeof(ref), "vma", frozenset())
    have = getattr(typeof(val), "vma", frozenset())
    need = tuple(a for a in want if a not in have)
    return jax.lax.pcast(val, need, to="varying") if need else val


def _expand_gqa(q, k, v):
    hq, hk = q.shape[2], k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


# ---------------------------------------------------------------------------
# Ring attention (local form — call inside shard_map over `axis_name`)
# ---------------------------------------------------------------------------


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None, mask=None):
    """Blockwise ring attention over a named mesh axis.

    q: local chunk (B, S/n, Hq, D); k/v: (B, S/n, Hkv, D) in the paddle
    flash-attention layout, sequence sharded contiguously over `axis_name`
    (chunk i = rank i's slice).  GQA k/v rotate at their narrow Hkv width —
    ppermute bytes are the cost ring attention must hide, so heads expand
    *after* each permute, locally.  Returns the local chunk (B, S/n, Hq, D).

    mask: optional (S/n, S) LOCAL-rows x GLOBAL-cols slice of an (S, S)
    attention mask (bool keep-mask or additive float); each ring step
    dynamically slices the column block belonging to the k/v chunk
    currently held, so arbitrary (document/blockwise) masks compose with
    the ring without ever materializing (S, S) per device pair.
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv
    Sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)

    # q: (B, Hkv, rep, S, D) f32 grouped layout — the GQA group rides as a
    # free dot_general dimension, so k/v are never expanded to Hq width.
    # k/v stay in their input dtype: ppermute bytes are the ring's cost, and
    # the MXU multiplies bf16 natively with f32 accumulation.
    qg = (jnp.swapaxes(q, 1, 2).astype(jnp.float32) * np.float32(scale)
          ).reshape(B, Hkv, rep, Sq, D)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    rows = idx * Sq + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
    cols_local = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
    # kv chunks travel to the NEXT rank each step: after t steps this rank
    # holds the chunk originally owned by rank (idx - t) mod n.
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        m, l, acc, kc, vc = carry
        s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, kc,
                       preferred_element_type=jnp.float32
                       ).reshape(B, H, Sq, Sk)
        src = jax.lax.rem(idx - t + n, n)
        if causal:
            cols = src * Sk + cols_local
            s = jnp.where((rows >= cols)[None, None], s, _NEG_INF)
        if mask is not None:
            blk = jax.lax.dynamic_slice(mask, (0, src * Sk), (Sq, Sk))
            if mask.dtype == jnp.bool_:
                s = jnp.where(blk[None, None], s, _NEG_INF)
            else:
                s = s + blk.astype(s.dtype)[None, None]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bgrqk,bgkd->bgrqd", p.reshape(B, Hkv, rep, Sq, Sk), vc,
            preferred_element_type=jnp.float32).reshape(B, H, Sq, D)
        kc = jax.lax.ppermute(kc, axis_name, fwd_perm)
        vc = jax.lax.ppermute(vc, axis_name, fwd_perm)
        return (m_new, l, acc, kc, vc), None

    m0 = _pvary_like(jnp.full((B, H, Sq), _NEG_INF, jnp.float32), qg)
    l0 = _pvary_like(jnp.zeros((B, H, Sq), jnp.float32), qg)
    a0 = _pvary_like(jnp.zeros((B, H, Sq, D), jnp.float32), qg)
    (m, l, acc, _, _), _ = jax.lax.scan(
        jax.checkpoint(step), (m0, l0, a0, kt, vt), jnp.arange(n))

    out = acc / jnp.maximum(l, _TINY)[..., None]
    if mask is not None:
        # a fully-masked row never saw a real score.  Detect it for BOTH
        # mask encodings with one threshold: bool masks leave m at the
        # -1e30 floor, additive "-1e9" masks leave m ~ -1e9 — while any
        # real row has m of order |q.k| (<< 1e8).  The same convention is
        # applied in kernels.attention_reference so ring and local paths
        # agree on degenerate rows (return 0, not NaN / uniform avg of v).
        out = jnp.where((m <= -1e8)[..., None], 0.0, out)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses all-to-all attention (local form)
# ---------------------------------------------------------------------------


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None, mask=None):
    """DeepSpeed-Ulysses: all-to-all seq<->head swap over `axis_name`.

    q, k, v: local chunks (B, S/n, H, D) with the (local) head counts
    divisible by the axis size.  Inside: (B, S, H/n, D) full-sequence
    attention (flash kernel eligible), then the inverse all-to-all restores
    sequence sharding.  GQA k/v travel at their narrow Hkv width when
    divisible (the local attention handles the head-group expansion).
    mask: optional full (S, S) mask (replicated — after the all-to-all the
    whole sequence is local, so it applies directly).
    """
    from ..kernels import attention as _local_attention

    n = jax.lax.psum(1, axis_name)
    if k.shape[2] % n:
        k, v = _expand_gqa(q, k, v)
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # split heads (axis 2) across the group, gather sequence (axis 1)
    q = a2a(q, split_axis=2, concat_axis=1)
    k = a2a(k, split_axis=2, concat_axis=1)
    v = a2a(v, split_axis=2, concat_axis=1)
    out = _local_attention(q, k, v, causal=causal, scale=scale, mask=mask)
    return a2a(out, split_axis=1, concat_axis=2)


# ---------------------------------------------------------------------------
# Global wrapper: shard_map over the standard mesh layout
# ---------------------------------------------------------------------------


def _batch_spec_axes(mesh: Mesh):
    axes = tuple(a for a in ("data", "sharding") if a in mesh.axis_names)
    return axes if axes else None


def manual_axes_in_context() -> frozenset:
    """Mesh axes already manual (inside an enclosing shard_map), else empty."""
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is None or not am.axis_names:
            return frozenset()
        return frozenset(
            a for a, t in zip(am.axis_names, am.axis_types)
            if t == jax.sharding.AxisType.Manual)
    except AttributeError:
        # older jax: no abstract-mesh tracking, but the named axes in scope
        # inside a shard_map/pmap body ARE its manual axes
        try:
            from jax._src import core as _core
            return frozenset(_core.get_axis_env().axis_sizes)
        except Exception:  # noqa: BLE001 — no axis env
            return frozenset()
    except Exception:  # noqa: BLE001 — no context mesh
        return frozenset()


def context_parallel_attention(q, k, v, mesh: Optional[Mesh] = None,
                               impl: str = "ring", causal: bool = True,
                               scale: Optional[float] = None,
                               seq_axis: str = "sep", mask=None):
    """Attention with the sequence dimension sharded over `seq_axis`.

    q: (B, S, Hq, D), k/v: (B, S, Hkv, D) global arrays (may already carry
    shardings; GSPMD reshards to the shard_map in_specs as needed).  Falls back
    to plain fused attention when the mesh has no sep axis.

    mask: optional GLOBAL (S, S) attention mask (bool keep-mask or additive
    float).  Under ring its rows shard with q and each ring step slices the
    matching column block; under ulysses it applies whole after the
    all-to-all.  Batched/per-head masks are not supported sharded — express
    those as (S, S) document masks or run without the sep axis.
    """
    in_manual = seq_axis in manual_axes_in_context()
    if mask is not None and mask.ndim != 2:
        raise ValueError(
            f"context-parallel attention takes a 2D (S, S) mask, got shape "
            f"{tuple(mask.shape)}; batched/per-head masks only work without "
            f"the sep axis")
    if (mask is not None and not in_manual
            and mask.shape != (q.shape[1],) * 2):
        # in the manual (already-sharded) path below the caller passes LOCAL
        # chunks — (S/n, S) for ring — so the global square check only
        # applies to the global wrapper
        raise ValueError(
            f"context-parallel attention takes a global (S, S) mask, got "
            f"shape {tuple(mask.shape)} for S={q.shape[1]}")

    # inside an enclosing shard_map that already made seq_axis manual (the
    # pipeline composes this way), run the local collective form directly.
    # NB here q/k/v (and any mask) are already LOCAL chunks of the caller's
    # making: ring wants mask rows local, ulysses wants the full mask.
    if in_manual:
        try:
            n_sep = jax.sharding.get_abstract_mesh().shape[seq_axis]
        except AttributeError:  # older jax: read the in-scope axis env
            from jax._src import core as _core
            n_sep = _core.get_axis_env().axis_sizes[seq_axis]
        if impl == "ulysses" and q.shape[2] % n_sep:
            if mask is not None:
                # the two impls take DIFFERENT local mask layouts (ring:
                # (S/n, S) rows; ulysses: full (S, S)) — a silent downgrade
                # would misread the caller's mask on every rank but 0
                raise ValueError(
                    "ulysses head count does not divide the sep axis and a "
                    "mask was passed; cannot downgrade to ring (its local "
                    "mask layout differs) — pass impl='ring' with (S/n, S) "
                    "mask rows instead")
            impl = "ring"  # same downgrade as the global wrapper below
        local = ring_attention if impl == "ring" else ulysses_attention
        return local(q, k, v, axis_name=seq_axis, causal=causal, scale=scale,
                     mask=mask)

    mesh = mesh or mesh_lib.get_global_mesh()
    if (mesh is None or seq_axis not in mesh.axis_names
            or mesh.shape[seq_axis] == 1):
        from ..kernels import attention as _local_attention
        return _local_attention(q, k, v, causal=causal, scale=scale, mask=mask)

    if impl == "ulysses" and mask is not None:
        # ring applies masks blockwise (never materializes (S, S) scores);
        # ulysses would fall off the flash path entirely (kernels.attention
        # takes the Pallas kernel only when mask is None) and build the full
        # score matrix — exactly what long-context parallelism must avoid
        impl = "ring"
    if impl == "ulysses":
        # the LOCAL head count (after any model-axis sharding) must split
        # evenly over the sep axis; otherwise ring still works
        tp = mesh.shape.get("model", 1)
        local_hq = q.shape[2] // tp
        if local_hq % mesh.shape[seq_axis] or q.shape[2] % tp:
            impl = "ring"
    local = ring_attention if impl == "ring" else ulysses_attention
    fn = functools.partial(local, axis_name=seq_axis, causal=causal, scale=scale)

    b = _batch_spec_axes(mesh)
    tp = mesh.shape.get("model", 1)
    # heads shard over model only when the NARROW (kv) head count divides tp;
    # otherwise both replicate — q-sharded with kv-replicated would break the
    # GQA group alignment inside the local kernels
    h = "model" if tp > 1 and k.shape[2] % tp == 0 else None
    spec = P(b, seq_axis, h, None)
    if mask is None:
        return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
    # ring: mask rows ride with q over seq_axis; ulysses sees it whole
    mask_spec = P(seq_axis, None) if local is ring_attention else P(None, None)
    return shard_map(lambda q_, k_, v_, m_: fn(q_, k_, v_, mask=m_),
                     mesh=mesh, in_specs=(spec, spec, spec, mask_spec),
                     out_specs=spec, check_vma=False)(q, k, v, mask)
