"""Distributed launcher + elastic supervisor.

Reference parity: `python/paddle/distributed/launch/main.py:18` (the
`python -m paddle.distributed.launch` CLI), the collective controller
(`launch/controllers/collective.py:37`), the rendezvous master
(`launch/controllers/master.py:73,186`) and the elastic manager
(`fleet/elastic/manager.py:126`).  TPU-native mapping:

  * one worker process per host-local chip set; env rendezvous hands each
    worker its (rank, world_size, coordinator) and `init_parallel_env` turns
    that into `jax.distributed.initialize` — the JAX coordination service is
    the "master" the reference implements by hand over etcd/TCP,
  * a tiny TCP KV store (`KVStore`) covers the multi-node barrier/rendezvous
    the reference's master.py does (node discovery before the JAX
    coordinator exists),
  * per-rank logs go to `<log_dir>/workerlog.<rank>` (reference layout),
  * the supervisor watches children; on a worker death it tears the job down
    and — with `--elastic` — relaunches the whole gang up to
    `--max_restarts` times, exporting PADDLE_RESTART_COUNT so training
    scripts resume from their latest checkpoint
    (`distributed.checkpoint.latest_step`).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

__all__ = ["LaunchConfig", "Controller", "KVStore", "KVClient", "main"]


# ---------------------------------------------------------------------------
# KV store — the rendezvous "master" (reference launch/controllers/master.py)
# ---------------------------------------------------------------------------


class _KVHandler(socketserver.StreamRequestHandler):
    def handle(self):
        store: Dict[str, str] = self.server.kv  # type: ignore[attr-defined]
        cond: threading.Condition = self.server.cond  # type: ignore[attr-defined]
        line = self.rfile.readline().decode().strip()
        if not line:
            return
        op, _, rest = line.partition(" ")
        if op == "SET":
            key, _, val = rest.partition(" ")
            with cond:
                store[key] = val
                cond.notify_all()
            self.wfile.write(b"OK\n")
        elif op == "GET":
            with cond:
                val = store.get(rest)
            self.wfile.write((f"{val}\n" if val is not None else "\n").encode())
        elif op == "WAIT":  # WAIT <timeout> <key>
            tmo_s, _, key = rest.partition(" ")
            deadline = time.time() + float(tmo_s)
            with cond:
                while key not in store and time.time() < deadline:
                    cond.wait(timeout=0.1)
                val = store.get(key)
            self.wfile.write((f"{val}\n" if val is not None else "\n").encode())
        elif op == "INCR":  # returns post-increment value
            with cond:
                cur = int(store.get(rest, "0")) + 1
                store[rest] = str(cur)
                cond.notify_all()
            self.wfile.write(f"{cur}\n".encode())


class KVStore:
    """Threaded TCP KV server for node rendezvous (SET/GET/WAIT/INCR)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _KVHandler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.kv = {}          # type: ignore[attr-defined]
        self._srv.cond = threading.Condition()  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        h, p = self._srv.server_address[:2]
        return f"{h}:{p}"

    def shutdown(self):
        self._srv.shutdown()
        self._srv.server_close()


class KVClient:
    def __init__(self, endpoint: str, connect_timeout: float = 300.0):
        host, _, port = endpoint.rpartition(":")
        self._addr = (host, int(port))
        self._connect_timeout = connect_timeout

    def _rt(self, line: str) -> str:
        # the master may come up AFTER this node (normal under real cluster
        # schedulers) — retry refused connections within the rendezvous window
        deadline = time.time() + self._connect_timeout
        while True:
            try:
                with socket.create_connection(self._addr, timeout=30) as s:
                    s.sendall((line + "\n").encode())
                    return s.makefile().readline().strip()
            except (ConnectionRefusedError, ConnectionResetError, OSError):
                if time.time() >= deadline:
                    raise
                time.sleep(0.2)

    def set(self, key: str, val: str):
        self._rt(f"SET {key} {val}")

    def get(self, key: str) -> Optional[str]:
        out = self._rt(f"GET {key}")
        return out or None

    def wait(self, key: str, timeout: float = 60.0) -> Optional[str]:
        out = self._rt(f"WAIT {timeout} {key}")
        return out or None

    def incr(self, key: str) -> int:
        return int(self._rt(f"INCR {key}"))


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class LaunchConfig:
    nproc_per_node: int = 1
    nnodes: int = 1
    node_rank: int = 0
    master: Optional[str] = None      # host:port of the KV store (multi-node)
    log_dir: str = "log"
    elastic: bool = False
    max_restarts: int = 3
    poll_interval: float = 0.2
    stop_grace: float = 10.0


class Controller:
    """Spawn the local worker gang, watch it, restart on failure (elastic).

    Reference: launch/controllers/collective.py:37 (CollectiveController
    .build_pod + watch loop) and fleet/elastic/manager.py:126.
    """

    def __init__(self, config: LaunchConfig):
        self.c = config
        self._kv: Optional[KVStore] = None

    # -- rendezvous ---------------------------------------------------------

    def _rendezvous(self, round_: int = 0) -> str:
        """Agree on the JAX coordinator address; returns 'host:port'.

        `round_` namespaces the KV keys so every elastic restart is a fresh
        rendezvous (a stale coordinator from the dead generation must not be
        reused).  The node-0 KV store is created once and reused across
        rounds — rebinding the master port would race the old listener.
        """
        c = self.c
        if c.nnodes <= 1:
            return f"127.0.0.1:{_free_port()}"
        if c.node_rank == 0:
            if self._kv is None:
                host, _, port = (c.master or "").rpartition(":")
                self._kv = KVStore(host or "0.0.0.0", int(port or 0))
            kv = KVClient(self._kv.endpoint if not c.master else c.master)
            coord = f"{socket.gethostname()}:{_free_port()}"
            kv.set(f"coordinator/{round_}", coord)
        else:
            kv = KVClient(c.master)
            coord = kv.wait(f"coordinator/{round_}", timeout=300)
            if not coord:
                raise TimeoutError("rendezvous: no coordinator published "
                                   f"at {c.master} within 300s")
        n = kv.incr(f"joined/{round_}")
        if n == c.nnodes:
            kv.set(f"all_joined/{round_}", "1")
        if not kv.wait(f"all_joined/{round_}", timeout=300):
            raise TimeoutError(f"rendezvous: {n}/{c.nnodes} nodes joined")
        return coord

    # -- spawn/watch --------------------------------------------------------

    def _spawn(self, argv: Sequence[str], coord: str,
               restart: int) -> List[subprocess.Popen]:
        c = self.c
        os.makedirs(c.log_dir, exist_ok=True)
        world = c.nnodes * c.nproc_per_node
        procs = []
        for local_rank in range(c.nproc_per_node):
            rank = c.node_rank * c.nproc_per_node + local_rank
            env = dict(os.environ)
            env.update({
                # paddle names (reference launch/job/pod env contract)
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_MASTER": coord,
                "PADDLE_RESTART_COUNT": str(restart),
                # generic + jax names
                "RANK": str(rank), "LOCAL_RANK": str(local_rank),
                "WORLD_SIZE": str(world),
                "JAX_COORDINATOR_ADDRESS": coord,
                "JAX_NUM_PROCESSES": str(world),
                "JAX_PROCESS_ID": str(rank),
            })
            log = open(os.path.join(c.log_dir, f"workerlog.{rank}"), "ab")
            log.write(f"==== restart {restart} ====\n".encode())
            log.flush()
            procs.append(subprocess.Popen(
                list(argv), env=env, stdout=log, stderr=subprocess.STDOUT))
        return procs

    def _stop(self, procs: List[subprocess.Popen]):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + self.c.stop_grace
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def _watch(self, procs: List[subprocess.Popen]) -> int:
        """0 when every worker exits cleanly; first bad rc otherwise."""
        while True:
            codes = [p.poll() for p in procs]
            bad = [rc for rc in codes if rc not in (None, 0)]
            if bad:
                self._stop(procs)
                return bad[0]
            if all(rc == 0 for rc in codes):
                return 0
            time.sleep(self.c.poll_interval)

    def run(self, argv: Sequence[str]) -> int:
        c = self.c
        restart = 0
        try:
            while True:
                coord = self._rendezvous(restart)
                procs = self._spawn(argv, coord, restart)
                rc = self._watch(procs)
                if rc == 0:
                    return 0
                if not c.elastic or restart >= c.max_restarts:
                    return rc
                restart += 1
                print(f"[launch] worker failed rc={rc}; elastic restart "
                      f"{restart}/{c.max_restarts}", file=sys.stderr)
        finally:
            if self._kv is not None:
                self._kv.shutdown()


def main(args: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed training job "
                    "(reference: paddle.distributed.launch)")
    ap.add_argument("--nproc_per_node", type=int,
                    default=int(os.environ.get("PADDLE_NPROC_PER_NODE", "1")))
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node_rank", type=int, default=0)
    ap.add_argument("--master", default=None,
                    help="host:port of the rendezvous KV store (multi-node)")
    ap.add_argument("--log_dir", default="log")
    ap.add_argument("--elastic", action="store_true",
                    help="restart the gang on worker failure")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("training_script")
    ap.add_argument("training_script_args", nargs=argparse.REMAINDER)
    ns = ap.parse_args(args)

    cfg = LaunchConfig(
        nproc_per_node=ns.nproc_per_node, nnodes=ns.nnodes,
        node_rank=ns.node_rank, master=ns.master, log_dir=ns.log_dir,
        elastic=ns.elastic, max_restarts=ns.max_restarts)
    if ns.training_script.endswith(".py"):
        argv = [sys.executable, ns.training_script, *ns.training_script_args]
    else:  # arbitrary executable
        argv = [ns.training_script, *ns.training_script_args]
    return Controller(cfg).run(argv)
