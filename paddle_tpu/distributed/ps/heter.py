"""Heterogeneous PS training (C50): CPU-hosted embeddings + TPU dense net.

Reference parity: the heterogeneous parameter server
(`paddle/fluid/framework/fleet/heter_context.h`, `ps/service/heter_client.cc`
/ `heter_server.cc`, BoxPS/HeterPS `box_wrapper.cu`): CPU machines hold the
huge sparse embedding tables, accelerator machines run the dense network,
and a heter pipeline moves the looked-up rows between them each step.

TPU-native mapping: the sparse half IS the `distributed.ps` stack (tables on
host/PS processes, reached through PSClient); the dense half is one jitted
XLA program on the TPU.  `HeterTrainer.step` is the pipeline:

    ids -> PSClient.pull_sparse (host/CPU)              # sparse pull
        -> jitted value_and_grad over (dense params, rows) on TPU
        -> dense update on device (functional AdamW, donated)
        -> PSClient.push_sparse with the row gradients  # sparse push

Only the (B, dim) looked-up block ever touches the chip, so table size is
bounded by PS host memory, not HBM — the exact capacity split the
reference's heter PS exists for.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...optimizer.functional import AdamW
from . import PSClient

__all__ = ["HeterTrainer"]


class HeterTrainer:
    """Joint sparse(PS)/dense(TPU) training step.

    dense_apply(dense_params, rows, batch) -> scalar loss, where `rows` is
    the (B, dim) embedding block for the batch's ids.  Dense params update
    on device with functional AdamW; sparse rows update server-side with
    the table's own SGD rule.
    """

    def __init__(self, client: PSClient, table_id: int, dim: int,
                 dense_params, dense_apply: Callable,
                 dense_optimizer: Optional[AdamW] = None,
                 table_kwargs: Optional[dict] = None):
        self.client = client
        self.table_id = table_id
        self.dim = dim
        client.create_sparse_table(table_id, dim, **(table_kwargs or {}))
        self.dense_params = jax.tree_util.tree_map(jnp.asarray, dense_params)
        self.opt = dense_optimizer or AdamW(learning_rate=1e-3)
        self.opt_state = self.opt.init(self.dense_params)

        def _step(params, opt_state, rows, batch):
            def loss_of(p, r):
                return dense_apply(p, r, batch)

            loss, (gp, gr) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(params, rows)
            new_params, new_state = self.opt.update(gp, opt_state, params)
            return loss, new_params, new_state, gr

        # no donation: with fp32 dense params the AdamW master weights
        # alias the param buffers, and donating both would donate one
        # buffer twice; the dense half here is small by construction
        self._step = jax.jit(_step)

    def step(self, ids, batch) -> float:
        """One heter pipeline step; returns the loss."""
        ids = np.asarray(ids).ravel()
        rows = jnp.asarray(self.client.pull_sparse(self.table_id, ids))
        loss, self.dense_params, self.opt_state, grow = self._step(
            self.dense_params, self.opt_state, rows, batch)
        self.client.push_sparse(self.table_id, ids, np.asarray(grow))
        return float(loss)
