"""Parameter server (C35): sharded sparse/dense tables for recsys training.

Reference parity: `paddle/fluid/distributed/ps/` — `PSServer`/`PSClient`
(service/server.h:63, ps_client.h:64), `MemorySparseTable`
(table/memory_sparse_table.cc), SGD rules (table/sparse_sgd_rule.cc:
naive/adagrad/adam), geo-async (table/memory_sparse_geo_table.cc) and the
`the_one_ps.py` runtime facade.  TPU-native mapping:

  * tables live in native C++ (`native/pstable.cpp`, bucketed hash map with
    per-bucket locks + per-slot SGD rules; numpy fallback when no
    toolchain),
  * transport is the `distributed.rpc` layer (itself on the native message
    bus) instead of brpc — `PSClient` shards ids over servers by
    `id %% num_servers` (the reference's `get_sparse_shard` modulo scheme)
    and scatters pull/push with `rpc_async`,
  * the dense path holds whole parameter blocks per table (reference
    memory_dense_table),
  * geo-async: workers accumulate local deltas and push merged deltas every
    `geo_steps` trains; the server adds them in (reference geo table
    semantics),
  * `fleet.init_server()/run_server()/init_worker()/stop_worker()` facade
    reads the PaddleCloud env contract (TRAINING_ROLE) like the_one_ps.

The TPU angle: embedding tables this large never fit HBM; workers pull the
rows a batch touches into a dense jnp array (MXU-friendly), run the jitted
dense model on TPU, and push sparse grads back — the same CPU-PS +
accelerator-dense split the reference's heterogeneous PS targets.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ... import native

__all__ = ["SparseTable", "DenseTable", "PSServer", "PSClient",
           "SparseEmbedding", "HeterTrainer", "init_server", "run_server",
           "init_worker", "stop_worker", "is_server", "is_worker"]


# ---------------------------------------------------------------------------
# tables (native with numpy fallback)
# ---------------------------------------------------------------------------


def _lib():
    lib = native.load("pstable")
    if lib is not None and not getattr(lib, "_pst_typed", False):
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.pst_create.restype = ctypes.c_void_p
        lib.pst_create.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                   ctypes.c_float, ctypes.c_float]
        lib.pst_pull.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int, f32p]
        lib.pst_push.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int, f32p]
        lib.pst_add.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int, f32p]
        lib.pst_assign.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int, f32p]
        lib.pst_size.restype = ctypes.c_longlong
        lib.pst_size.argtypes = [ctypes.c_void_p]
        lib.pst_export.restype = ctypes.c_longlong
        lib.pst_export.argtypes = [ctypes.c_void_p, i64p, f32p,
                                   ctypes.c_longlong]
        lib.pst_save.restype = ctypes.c_int
        lib.pst_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pst_load.restype = ctypes.c_int
        lib.pst_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pst_destroy.argtypes = [ctypes.c_void_p]
        lib.pdt_create.restype = ctypes.c_void_p
        lib.pdt_create.argtypes = [ctypes.c_longlong, ctypes.c_char_p,
                                   ctypes.c_float]
        lib.pdt_pull.argtypes = [ctypes.c_void_p, f32p]
        lib.pdt_push.argtypes = [ctypes.c_void_p, f32p]
        lib.pdt_assign.argtypes = [ctypes.c_void_p, f32p]
        lib.pdt_destroy.argtypes = [ctypes.c_void_p]
        lib._pst_typed = True
    return lib


class _NumpyRuleMixin:
    """The same per-slot SGD rules as native/pstable.cpp, in numpy."""

    def _init_opt_state(self, shape):
        if self.optimizer == "adagrad":
            return {"g2": np.zeros(shape, np.float32)}
        if self.optimizer == "adam":
            return {"m": np.zeros(shape, np.float32),
                    "v": np.zeros(shape, np.float32),
                    "b1p": np.float32(1.0), "b2p": np.float32(1.0)}
        return {}

    def _apply(self, w, g, st):
        if self.optimizer == "adagrad":
            st["g2"] += g * g
            w -= self.lr * g / (np.sqrt(st["g2"]) + 1e-8)
        elif self.optimizer == "adam":
            b1, b2 = 0.9, 0.999
            st["b1p"] = np.float32(st["b1p"] * b1)
            st["b2p"] = np.float32(st["b2p"] * b2)
            st["m"] = b1 * st["m"] + (1 - b1) * g
            st["v"] = b2 * st["v"] + (1 - b2) * g * g
            mhat = st["m"] / (1 - st["b1p"])
            vhat = st["v"] / (1 - st["b2p"])
            w -= self.lr * mhat / (np.sqrt(vhat) + 1e-8)
        else:
            w -= self.lr * g


class SparseTable(_NumpyRuleMixin):
    """Lazy-init sparse embedding table (memory_sparse_table.cc analog)."""

    def __init__(self, dim: int, optimizer: str = "sgd", lr: float = 0.01,
                 initial_range: float = 0.0, backend: str = "auto"):
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unsupported sparse optimizer {optimizer!r}")
        self.dim, self.optimizer, self.lr = dim, optimizer, lr
        self.initial_range = initial_range
        lib = _lib() if backend in ("auto", "native") else None
        if backend == "native" and lib is None:
            raise RuntimeError("native pstable unavailable (no toolchain)")
        self._lib = lib
        if lib is not None:
            self._h = lib.pst_create(dim, optimizer.encode(), lr,
                                     initial_range)
            self.backend = "native"
        else:
            self._rows: Dict[int, np.ndarray] = {}
            self._opt_state: Dict[int, dict] = {}
            self._mu = threading.Lock()
            self.backend = "python"

    # deterministic per-id init, matching native splitmix64 only in spirit
    def _init_row(self, id_: int) -> np.ndarray:
        if self.initial_range == 0.0:
            return np.zeros(self.dim, np.float32)
        rng = np.random.default_rng(np.uint64(id_) + np.uint64(0x51A9B2C3))
        return ((2.0 * rng.random(self.dim) - 1.0)
                * self.initial_range).astype(np.float32)

    def pull(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        out = np.empty((ids.size, self.dim), np.float32)
        if self._lib is not None:
            self._lib.pst_pull(self._h, ids, ids.size, out)
            return out
        with self._mu:
            for i, id_ in enumerate(ids):
                r = self._rows.get(int(id_))
                if r is None:
                    r = self._rows[int(id_)] = self._init_row(int(id_))
                    self._opt_state[int(id_)] = self._init_opt_state(
                        (self.dim,))
                out[i] = r
        return out

    def push(self, ids, grads):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            ids.size, self.dim)
        if self._lib is not None:
            self._lib.pst_push(self._h, ids, ids.size, grads)
            return
        with self._mu:
            for i, id_ in enumerate(ids):
                if int(id_) not in self._rows:
                    self._rows[int(id_)] = self._init_row(int(id_))
                    self._opt_state[int(id_)] = self._init_opt_state(
                        (self.dim,))
                self._apply(self._rows[int(id_)], grads[i],
                            self._opt_state[int(id_)])

    def add(self, ids, deltas):
        """w[id] += delta atomically (geo-async merge)."""
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        deltas = np.ascontiguousarray(deltas, np.float32).reshape(
            ids.size, self.dim)
        if self._lib is not None:
            self._lib.pst_add(self._h, ids, ids.size, deltas)
            return
        with self._mu:
            for i, id_ in enumerate(ids):
                r = self._rows.get(int(id_))
                if r is None:
                    r = self._rows[int(id_)] = self._init_row(int(id_))
                    self._opt_state[int(id_)] = self._init_opt_state(
                        (self.dim,))
                r += deltas[i]

    def assign(self, ids, vals):
        """Overwrite weights (no optimizer step) — load path."""
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        vals = np.ascontiguousarray(vals, np.float32).reshape(
            ids.size, self.dim)
        if self._lib is not None:
            self._lib.pst_assign(self._h, ids, ids.size, vals)
            return
        with self._mu:
            for i, id_ in enumerate(ids):
                self._rows[int(id_)] = vals[i].copy()
                self._opt_state.setdefault(
                    int(id_), self._init_opt_state((self.dim,)))

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            self._h = None
            try:
                lib.pst_destroy(h)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass

    def __len__(self) -> int:
        if self._lib is not None:
            return int(self._lib.pst_size(self._h))
        with self._mu:
            return len(self._rows)

    def export(self):
        """(ids (N,), weights (N, dim)) snapshot of every touched row."""
        if self._lib is not None:
            n = len(self)
            ids = np.empty(n, np.int64)
            out = np.empty((n, self.dim), np.float32)
            n = int(self._lib.pst_export(self._h, ids, out, n))
            return ids[:n], out[:n]
        with self._mu:
            ids = np.fromiter(self._rows, np.int64, len(self._rows))
        return ids, self.pull(ids)

    def save(self, path: str):
        """Weights-only snapshot (optimizer slots rebuild on demand, like
        the reference's converter-based save).  The native format is the
        C++ binary layout; the fallback writes npz with the same content."""
        if self._lib is not None:
            rc = self._lib.pst_save(self._h, path.encode())
            if rc != 0:
                raise OSError(f"pst_save({path}) failed rc={rc}")
            return
        ids, w = self.export()
        with open(path, "wb") as f:
            np.savez(f, ids=ids, w=w)

    def load(self, path: str):
        if self._lib is not None:
            rc = self._lib.pst_load(self._h, path.encode())
            if rc != 0:
                raise OSError(f"pst_load({path}) failed rc={rc} "
                              f"(missing file or dim mismatch)")
            return
        with np.load(path) as z:
            if z["w"].shape[1] != self.dim:
                raise OSError(f"pst_load({path}): dim mismatch")
            self.assign(z["ids"], z["w"])


class DenseTable(_NumpyRuleMixin):
    """One flat parameter block (memory_dense_table.cc analog)."""

    def __init__(self, size: int, optimizer: str = "sgd", lr: float = 0.01,
                 backend: str = "auto"):
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unsupported dense optimizer {optimizer!r}")
        self.size, self.optimizer, self.lr = int(size), optimizer, lr
        lib = _lib() if backend in ("auto", "native") else None
        if backend == "native" and lib is None:
            raise RuntimeError("native pstable unavailable (no toolchain)")
        self._lib = lib
        if lib is not None:
            self._h = lib.pdt_create(self.size, optimizer.encode(), lr)
            if not self._h:
                raise ValueError(
                    f"dense table size {self.size} out of range for the "
                    f"native backend (must be in [1, (2^31-4)/3])")
            self.backend = "native"
        else:
            self._w = np.zeros(self.size, np.float32)
            self._st = self._init_opt_state((self.size,))
            self._mu = threading.Lock()
            self.backend = "python"

    def pull(self) -> np.ndarray:
        out = np.empty(self.size, np.float32)
        if self._lib is not None:
            self._lib.pdt_pull(self._h, out)
            return out
        with self._mu:
            out[:] = self._w
        return out

    def push(self, grad):
        grad = np.ascontiguousarray(grad, np.float32).ravel()
        if grad.size != self.size:
            raise ValueError(f"dense push size {grad.size} != {self.size}")
        if self._lib is not None:
            self._lib.pdt_push(self._h, grad)
            return
        with self._mu:
            self._apply(self._w, grad, self._st)

    def assign(self, vals):
        vals = np.ascontiguousarray(vals, np.float32).ravel()
        if vals.size != self.size:
            raise ValueError(f"dense assign size {vals.size} != {self.size}")
        if self._lib is not None:
            self._lib.pdt_assign(self._h, vals)
            return
        with self._mu:
            self._w[:] = vals

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            self._h = None
            try:
                lib.pdt_destroy(h)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class PSServer:
    """Hosts tables; methods are what PSClient invokes (over rpc or
    directly in local mode).  Reference: ps/service/server.h:63."""

    def __init__(self):
        self._sparse: Dict[int, SparseTable] = {}
        self._dense: Dict[int, DenseTable] = {}

    # constructor defaults — omitted kwargs in a re-attach compare against
    # THESE (what the same call would have created), not the existing
    # value; derived from the signatures so they cannot drift
    import inspect as _inspect
    _SPARSE_DEFAULTS = {
        n: p.default for n, p in
        _inspect.signature(SparseTable.__init__).parameters.items()
        if n in ("optimizer", "lr", "initial_range")}
    _DENSE_DEFAULTS = {
        n: p.default for n, p in
        _inspect.signature(DenseTable.__init__).parameters.items()
        if n in ("optimizer", "lr")}
    del _inspect

    @staticmethod
    def _check_same_config(kind, table_id, existing, requested, defaults):
        for name, have in existing.items():
            want = requested.get(name, defaults.get(name, have))
            if want != have:
                raise ValueError(
                    f"{kind} table {table_id} exists with {name}={have!r}, "
                    f"requested {want!r} — a re-attaching trainer must use "
                    f"the table's original configuration")

    def create_sparse_table(self, table_id: int, dim: int, **kw):
        """Idempotent: a table that already exists with the SAME config is
        KEPT (a second/re-attached trainer must not wipe trained rows); any
        config mismatch — dim, optimizer, lr, initial_range — raises."""
        existing = self._sparse.get(table_id)
        if existing is not None:
            self._check_same_config(
                "sparse", table_id,
                {"dim": existing.dim, "optimizer": existing.optimizer,
                 "lr": existing.lr, "initial_range": existing.initial_range},
                dict(kw, dim=dim), self._SPARSE_DEFAULTS)
            return
        self._sparse[table_id] = SparseTable(dim, **kw)

    def create_dense_table(self, table_id: int, size: int, **kw):
        existing = self._dense.get(table_id)
        if existing is not None:
            self._check_same_config(
                "dense", table_id,
                {"size": existing.size, "optimizer": existing.optimizer,
                 "lr": existing.lr},
                dict(kw, size=size), self._DENSE_DEFAULTS)
            return
        self._dense[table_id] = DenseTable(size, **kw)

    def pull_sparse(self, table_id: int, ids) -> np.ndarray:
        return self._sparse[table_id].pull(ids)

    def push_sparse(self, table_id: int, ids, grads):
        self._sparse[table_id].push(ids, grads)

    def push_sparse_delta(self, table_id: int, ids, deltas):
        """Geo-async merge: w[id] += delta (no optimizer state).  Atomic
        per row — concurrent trainer flushes for the same id both land."""
        self._sparse[table_id].add(ids, deltas)

    def table_lr(self, table_id: int) -> float:
        return self._sparse[table_id].lr

    def sparse_table_size(self, table_id: int) -> int:
        """Rows materialized so far (lazy init: only touched ids exist)."""
        return len(self._sparse[table_id])

    def pull_dense(self, table_id: int) -> np.ndarray:
        return self._dense[table_id].pull()

    def push_dense(self, table_id: int, grad):
        self._dense[table_id].push(grad)

    def save(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        for tid, t in self._sparse.items():
            t.save(os.path.join(dirname, f"sparse_{tid}.bin"))
        for tid, t in self._dense.items():
            np.save(os.path.join(dirname, f"dense_{tid}.npy"), t.pull())

    def load(self, dirname: str):
        for tid, t in self._sparse.items():
            p = os.path.join(dirname, f"sparse_{tid}.bin")
            if os.path.exists(p):
                t.load(p)
        for tid, t in self._dense.items():
            p = os.path.join(dirname, f"dense_{tid}.npy")
            if os.path.exists(p):
                t.assign(np.load(p))


# the server singleton rpc-dispatched functions act on (one per process,
# like the reference's server instance behind brpc)
_SERVER: Optional[PSServer] = None


def _server() -> PSServer:
    if _SERVER is None:
        raise RuntimeError("this process runs no PSServer (call init_server)")
    return _SERVER


def _rpc_create_sparse(table_id, dim, kw):
    _server().create_sparse_table(table_id, dim, **kw)


def _rpc_create_dense(table_id, size, kw):
    _server().create_dense_table(table_id, size, **kw)


def _rpc_pull_sparse(table_id, ids):
    return _server().pull_sparse(table_id, ids)


def _rpc_push_sparse(table_id, ids, grads):
    _server().push_sparse(table_id, ids, grads)


def _rpc_push_sparse_delta(table_id, ids, deltas):
    _server().push_sparse_delta(table_id, ids, deltas)


def _rpc_table_lr(table_id):
    return _server().table_lr(table_id)


def _rpc_pull_dense(table_id):
    return _server().pull_dense(table_id)


def _rpc_push_dense(table_id, grad):
    _server().push_dense(table_id, grad)


def _rpc_save(dirname):
    _server().save(dirname)


def _rpc_load(dirname):
    _server().load(dirname)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class PSClient:
    """Shards ids over servers by `id %% num_servers` and scatters
    pull/push (reference ps_client.h:64 + get_sparse_shard modulo).

    `servers`: list of rpc worker names (remote mode) OR PSServer objects
    (local mode, single process — used by tests and by SparseEmbedding's
    default); `geo_steps > 0` switches push_sparse into geo-async delta
    accumulation flushed every geo_steps trains.
    """

    def __init__(self, servers: Sequence, geo_steps: int = 0):
        if not servers:
            raise ValueError("PSClient needs at least one server")
        self.servers = list(servers)
        self.remote = isinstance(self.servers[0], str)
        self.geo_steps = geo_steps
        self._geo_acc: Dict[int, Dict[int, np.ndarray]] = {}
        self._geo_count = 0
        self._table_lr: Dict[int, float] = {}
        self._dense_home: Dict[int, int] = {}

    # -- plumbing -----------------------------------------------------------

    def _call(self, server_idx: int, fn, *args, wait=True):
        assert self.remote, "local mode calls server methods directly"
        from .. import rpc
        if wait:
            return rpc.rpc_sync(self.servers[server_idx], fn, args=args)
        return rpc.rpc_async(self.servers[server_idx], fn, args=args)

    def _shard(self, ids: np.ndarray):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        home = (ids % len(self.servers)).astype(np.int64)
        return ids, home

    # -- tables -------------------------------------------------------------

    def create_sparse_table(self, table_id: int, dim: int, **kw):
        for i, s in enumerate(self.servers):
            if self.remote:
                self._call(i, _rpc_create_sparse, table_id, dim, kw)
            else:
                s.create_sparse_table(table_id, dim, **kw)
        self._table_lr[table_id] = kw.get("lr", 0.01)
        self._geo_acc.setdefault(table_id, {})

    def create_dense_table(self, table_id: int, size: int, **kw):
        # dense blocks live whole on one server, round-robin by table id
        home = table_id % len(self.servers)
        self._dense_home[table_id] = home
        if self.remote:
            self._call(home, _rpc_create_dense, table_id, size, kw)
        else:
            self.servers[home].create_dense_table(table_id, size, **kw)

    # -- sparse -------------------------------------------------------------

    def pull_sparse(self, table_id: int, ids) -> np.ndarray:
        ids, home = self._shard(ids)
        out = np.empty((ids.size, 0), np.float32)
        futures = []
        for si in range(len(self.servers)):
            mask = home == si
            if not mask.any():
                futures.append(None)
                continue
            if self.remote:
                futures.append((mask, self._call(
                    si, _rpc_pull_sparse, table_id, ids[mask], wait=False)))
            else:
                futures.append((mask, self.servers[si].pull_sparse(
                    table_id, ids[mask])))
        for item in futures:
            if item is None:
                continue
            mask, rows = item
            if self.remote:
                rows = rows.wait()
            if out.shape[1] == 0:
                out = np.empty((ids.size, rows.shape[1]), np.float32)
            out[mask] = rows
        return out

    def push_sparse(self, table_id: int, ids, grads):
        """Sync mode: apply the server-side SGD rule now.  Geo mode
        (geo_steps > 0): accumulate -lr*grad deltas locally, flush every
        geo_steps pushes."""
        if self.geo_steps > 0:
            self._geo_accumulate(table_id, ids, grads)
            return
        ids, home = self._shard(ids)
        grads = np.ascontiguousarray(grads, np.float32).reshape(ids.size, -1)
        futs = []
        for si in range(len(self.servers)):
            mask = home == si
            if not mask.any():
                continue
            if self.remote:
                futs.append(self._call(si, _rpc_push_sparse, table_id,
                                       ids[mask], grads[mask], wait=False))
            else:
                self.servers[si].push_sparse(table_id, ids[mask], grads[mask])
        for f in futs:
            f.wait()

    def _geo_accumulate(self, table_id: int, ids, grads):
        acc = self._geo_acc.setdefault(table_id, {})
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(ids.size, -1)
        # local SGD step at the table's configured lr becomes the delta the
        # server adds in (geo tables carry no optimizer state server-side).
        # A client that did not create the table (re-attached worker) asks
        # the server once — every trainer must step at the SAME lr.
        lr = self._table_lr.get(table_id)
        if lr is None:
            if self.remote:
                lr = self._call(table_id % len(self.servers), _rpc_table_lr,
                                table_id)
            else:
                lr = self.servers[0].table_lr(table_id)
            self._table_lr[table_id] = lr
        for i, id_ in enumerate(ids):
            d = acc.get(int(id_))
            delta = -lr * grads[i]
            acc[int(id_)] = delta if d is None else d + delta
        self._geo_count += 1
        if self._geo_count >= self.geo_steps:
            self.geo_flush()

    def geo_flush(self):
        """Push accumulated deltas; servers merge w += delta."""
        for table_id, acc in self._geo_acc.items():
            if not acc:
                continue
            ids = np.fromiter(acc, np.int64, len(acc))
            deltas = np.stack([acc[int(i)] for i in ids])
            sids, home = self._shard(ids)
            for si in range(len(self.servers)):
                mask = home == si
                if not mask.any():
                    continue
                if self.remote:
                    self._call(si, _rpc_push_sparse_delta, table_id,
                               sids[mask], deltas[mask])
                else:
                    self.servers[si].push_sparse_delta(
                        table_id, sids[mask], deltas[mask])
            acc.clear()
        self._geo_count = 0

    # -- dense --------------------------------------------------------------

    def pull_dense(self, table_id: int) -> np.ndarray:
        home = self._dense_home[table_id]
        if self.remote:
            return self._call(home, _rpc_pull_dense, table_id)
        return self.servers[home].pull_dense(table_id)

    def push_dense(self, table_id: int, grad):
        home = self._dense_home[table_id]
        if self.remote:
            self._call(home, _rpc_push_dense, table_id, grad)
        else:
            self.servers[home].push_dense(table_id, grad)

    # -- persistence --------------------------------------------------------

    def save(self, dirname: str):
        for si in range(len(self.servers)):
            d = os.path.join(dirname, f"server_{si}")
            if self.remote:
                self._call(si, _rpc_save, d)
            else:
                self.servers[si].save(d)

    def load(self, dirname: str):
        for si in range(len(self.servers)):
            d = os.path.join(dirname, f"server_{si}")
            if self.remote:
                self._call(si, _rpc_load, d)
            else:
                self.servers[si].load(d)


# ---------------------------------------------------------------------------
# embedding helper: the worker-side TPU data flow
# ---------------------------------------------------------------------------


class SparseEmbedding:
    """PS-backed embedding lookup for the training hot loop.

    `lookup(ids)` pulls the touched rows as a dense (N, dim) jnp array
    (device-placed, MXU-ready); `push_grad(ids, grad)` sends sparse grads
    back.  This is the reference's distributed embedding
    (`fleet.utils.ps_util` / c_embedding-over-PS) expressed TPU-first: the
    sparse side stays on host CPU, only dense batch slices touch the chip.
    """

    def __init__(self, client: PSClient, table_id: int, dim: int, **kw):
        self.client, self.table_id, self.dim = client, table_id, dim
        client.create_sparse_table(table_id, dim, **kw)

    def lookup(self, ids):
        import jax.numpy as jnp
        ids = np.asarray(ids)
        rows = self.client.pull_sparse(self.table_id, ids.ravel())
        return jnp.asarray(rows.reshape(*ids.shape, self.dim))

    def push_grad(self, ids, grad):
        ids = np.asarray(ids).ravel()
        g = np.asarray(grad, np.float32).reshape(ids.size, self.dim)
        self.client.push_sparse(self.table_id, ids, g)


# ---------------------------------------------------------------------------
# the_one_ps-style fleet facade (PaddleCloud env contract)
# ---------------------------------------------------------------------------


def is_server() -> bool:
    return os.environ.get("TRAINING_ROLE", "").upper() == "PSERVER"


def is_worker() -> bool:
    return os.environ.get("TRAINING_ROLE", "TRAINER").upper() == "TRAINER"


def init_server(load_dir: Optional[str] = None) -> PSServer:
    """Create this process's PSServer (reference fleet.init_server)."""
    global _SERVER
    if _SERVER is None:
        _SERVER = PSServer()
    if load_dir:
        _SERVER.load(load_dir)
    return _SERVER


def run_server(name: Optional[str] = None, rank: Optional[int] = None,
               world_size: Optional[int] = None,
               master_endpoint: Optional[str] = None):
    """Join the rpc gang as a server and serve until rpc.shutdown()
    (reference fleet.run_server blocks in brpc)."""
    from .. import rpc
    init_server()
    rpc.init_rpc(name or f"ps_server_{os.environ.get('PADDLE_TRAINER_ID', 0)}",
                 rank, world_size, master_endpoint)
    rpc.shutdown()  # barrier-blocks until every worker is done, then exits


def init_worker(server_names: List[str], geo_steps: int = 0,
                name: Optional[str] = None, rank: Optional[int] = None,
                world_size: Optional[int] = None,
                master_endpoint: Optional[str] = None) -> PSClient:
    """Join the rpc gang as a trainer; returns the PSClient."""
    from .. import rpc
    rpc.init_rpc(name or f"trainer_{os.environ.get('PADDLE_TRAINER_ID', 0)}",
                 rank, world_size, master_endpoint)
    return PSClient(server_names, geo_steps=geo_steps)


def stop_worker():
    from .. import rpc
    rpc.shutdown()


from .heter import HeterTrainer  # noqa: E402  (C50: CPU sparse + TPU dense)
