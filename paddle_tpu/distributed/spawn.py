"""paddle.distributed.spawn — in-Python multi-process launch (C33 sibling).

Reference parity: `python/paddle/distributed/spawn.py:454` (spawn(func,
args, nprocs) starting one training process per device with the env
contract set).  TPU-native mapping: each child gets the launcher's env
contract (PADDLE_TRAINER_ID / RANK / JAX_COORDINATOR_ADDRESS ...) so
`init_parallel_env` / `rpc.init_rpc` work unchanged; processes use the
`spawn` start method (fork is unsafe once a JAX backend is live).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
from typing import Optional, Sequence

__all__ = ["spawn"]


def _child(func, args, rank, nprocs, coord, env_extra):
    os.environ.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_LOCAL_RANK": str(rank),
        "PADDLE_MASTER": coord,
        "RANK": str(rank), "LOCAL_RANK": str(rank),
        "WORLD_SIZE": str(nprocs),
        "JAX_COORDINATOR_ADDRESS": coord,
        "JAX_NUM_PROCESSES": str(nprocs),
        "JAX_PROCESS_ID": str(rank),
        **(env_extra or {}),
    })
    func(*args)


def spawn(func, args: Sequence = (), nprocs: int = 1,
          join: bool = True, env: Optional[dict] = None,
          timeout: Optional[float] = None):
    """Run `func(*args)` in `nprocs` fresh processes with the distributed
    env contract set (reference spawn.py).  Returns the context (list of
    processes) when join=False; raises if any child exits nonzero."""
    with socket.socket() as s:
        # NB probe-then-release has an inherent TOCTOU window before rank0
        # binds the coordinator (same as the launcher's _free_port and the
        # reference's get_free_port); SO_REUSEADDR at least lets rank0
        # rebind through TIME_WAIT remnants
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_child,
                         args=(func, tuple(args), rank, nprocs, coord, env),
                         daemon=False)
             for rank in range(nprocs)]
    for p in procs:
        p.start()
    if not join:
        return procs
    # watch loop (launcher-style): the FIRST failure dooms the gang — a
    # sequential join(None) would hang forever on a sibling blocked waiting
    # for the dead worker (e.g. rank1 waiting on rank0's coordinator)
    import time
    deadline = None if timeout is None else time.time() + timeout
    failed = []
    while True:
        codes = [p.exitcode for p in procs]
        failed = [(r, rc) for r, rc in enumerate(codes)
                  if rc not in (None, 0)]
        if failed or all(rc == 0 for rc in codes):
            break
        if deadline is not None and time.time() > deadline:
            failed = [(r, "timeout") for r, rc in enumerate(codes)
                      if rc is None]
            break
        time.sleep(0.1)
    if failed:
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(5)
        raise RuntimeError(f"spawn: workers failed: {failed}")
    return procs
