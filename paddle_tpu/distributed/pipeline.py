"""Pipeline parallelism over the `pipe` mesh axis — single-jit SPMD schedules.

Reference analog: fleet.meta_parallel.PipelineParallel
(fleet/meta_parallel/pipeline_parallel.py:132, 1F1B at :387, interleaved at
:822,1016) and the P2P layer (pp_utils/p2p_communication.py:302) built on
NCCL batch_isend_irecv.  The TPU has no NCCL p2p; the idiomatic design
(SURVEY.md §7 "Hard parts") runs the WHOLE microbatch schedule inside one jit:

  * layer-stacked params are sharded over `pipe` (each stage owns L/P layers),
  * activations move stage-to-stage with `jax.lax.ppermute` (neighbor ICI hop),
  * `shard_map` is MANUAL only over `pipe` — every other axis stays `auto`,
    so tensor/sequence/data sharding inside a stage is still pure GSPMD.

Two schedules, mirroring the reference's FThenB / 1F1B pair:

  * ``pipeline_apply`` — GPipe wavefront (`lax.scan` shift register), with
    optional INTERLEAVED virtual stages (stage s owns layer chunks
    s, s+P, s+2P, …; one unified scan of V·M + P − 1 ticks, so the bubble is
    P−1 ticks regardless of V·M — the reference's interleaved 1F1B bubble,
    pipeline_parallel.py:822).  Differentiable: `jax.grad` through the scan
    materializes the reverse schedule (activation stash = M microbatches,
    GPipe's memory profile; use remat to trade).
  * ``pipeline_1f1b`` — a hand-scheduled one-forward-one-backward train step
    that computes grads ITSELF (no autodiff through the schedule).  Each
    stage stashes at most P microbatch activations (the 1F1B memory bound;
    asserted by tests), recomputes the stage forward at the backward tick
    (recompute-everything 1F1B, like the reference's
    enable_recompute+pp), and accumulates param grads in-register.  Costs
    one extra stage-forward per tick vs GPipe-by-AD — it trades compute for
    the O(P) activation bound, which is what you want at long S / deep L.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from ._shard_map_compat import shard_map, typeof
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib


def _stage_param_specs(stacked_params, axis: str):
    """P(axis) on the leading (layer) dim of every leaf."""
    return jax.tree.map(lambda _: P(axis), stacked_params)


def _half(dt):
    return dt in (jnp.bfloat16, jnp.float16)


# Test escape hatch: tests/test_distributed.py's
# test_native_bf16_tp_pp_cpu_bug_still_present re-runs the NATIVE bf16
# tp x pp composition in a subprocess with this True — the day the XLA CPU
# bug below is fixed, that test FAILS with a "now WORKS" message and this
# workaround can be deleted.
FORCE_NATIVE_DTYPE_ON_CPU = False


def _cpu_needs_f32(mesh, axis, manual_axes, *trees):
    """XLA's CPU SPMD partitioner check-fails (hlo_instruction.cc 'Invalid
    binary instruction opcode copy') on half-precision programs under
    partial-manual shard_map when another mesh axis stays auto — the tp x pp
    composition (AD/GSPMD-inserted bf16 collectives trigger it, so no local
    wrapper can help).  The virtual CPU mesh is a correctness harness:
    upcast the whole pipelined computation to f32 there.  Real TPU runs the
    native dtype — bf16 tp x pp numerics therefore only ever execute as
    bf16 on TPU, a risk recorded in ARCHITECTURE.md.  `trees`: every input
    whose leaves could be half (a half PARAM with f32 activations still
    produces half AD collectives)."""
    if FORCE_NATIVE_DTYPE_ON_CPU:
        return False
    if jax.default_backend() != "cpu":
        return False
    if not any(_half(l.dtype) for t in trees for l in jax.tree.leaves(t)
               if hasattr(l, "dtype")):
        return False
    return any(mesh.shape[a] > 1 for a in mesh.axis_names
               if a != axis and a not in manual_axes)


def _upcast_tree(tree):
    return jax.tree.map(
        lambda a: a.astype(jnp.float32)
        if hasattr(a, "dtype") and _half(a.dtype) else a, tree)


def num_stages(mesh: Mesh, axis: str = "pipe") -> int:
    return mesh.shape.get(axis, 1) if axis in mesh.axis_names else 1


def _vma(val):
    return tuple(getattr(typeof(val), "vma", frozenset()))


def _pcast_to(val, vary):
    cur = getattr(typeof(val), "vma", frozenset())
    need = tuple(a for a in vary if a not in cur)
    if not need or not hasattr(jax.lax, "pcast"):
        return val  # legacy jax: no vma types, nothing to cast
    return jax.lax.pcast(val, need, to="varying")


def _wrap_block(block_fn, returns_aux: bool):
    """Normalize block_fn to always return (h, aux_scalar)."""
    if returns_aux:
        return block_fn

    def fn(h, lp, *ex):
        return block_fn(h, lp, *ex), jnp.float32(0.0)

    return fn


def _make_local_layers(blk):
    """Per-stage layer stack: scan blk over the local layer slice, summing
    aux (shared by both schedules)."""
    def local_layers(stage_params, h, *ex):
        def body(carry, lp):
            h, aux = carry
            h, a = blk(h, lp, *ex)
            return (h, _pcast_to(aux + a, _vma(h))), None
        aux0 = _pcast_to(jnp.float32(0.0), _vma(h))
        (out, aux), _ = jax.lax.scan(body, (h, aux0), stage_params)
        return out, aux
    return local_layers


def pipeline_apply(block_fn, stacked_params, x, extras: Sequence[Any] = (),
                   mesh: Optional[Mesh] = None, axis: str = "pipe",
                   n_micro: Optional[int] = None, remat: bool = True,
                   manual_axes: Sequence[str] = (),
                   x_spec: Optional[P] = None,
                   extras_specs: Optional[Sequence[P]] = None,
                   virtual_stages: int = 1,
                   returns_aux: bool = False):
    """Run `x` through L stacked layers, pipelined over the `axis` mesh axis.

    block_fn(h, layer_params, *extras) -> h'  (or (h', aux) if returns_aux)
    stacked_params: pytree with leading layer dim L on every leaf
                    (L % (P * virtual_stages) == 0)
    x: (B, ...) activations; microbatched along B (B % n_micro == 0)
    extras: replicated side inputs (rope tables, masks, ...)

    virtual_stages=V > 1 interleaves: stage s owns layer chunks s, s+P, …,
    s+(V-1)P and microbatches re-enter stage 0 after each chunk round — one
    scan of V·M + P − 1 ticks (bubble P−1 ticks, the interleaved-schedule
    profile of pipeline_parallel.py:822 — V× less bubble per unit work).

    manual_axes: additional mesh axes to make manual inside the stage body —
    used to compose with ring/Ulysses attention, whose `sep` collectives must
    see a manual axis.  When set, x_spec (spec of x WITHOUT the microbatch
    dim, e.g. P(None, 'sep', None) for seq-sharded activations) and
    extras_specs describe how those inputs are sharded over the manual axes.

    Returns activations shaped like x (plus the summed aux loss when
    returns_aux).  With no live pipe axis this reduces to a plain lax.scan
    over layers.
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    pp = num_stages(mesh, axis) if mesh is not None else 1
    blk = _wrap_block(block_fn, returns_aux)

    if remat:
        blk = jax.checkpoint(blk)

    local_layers = _make_local_layers(blk)

    if pp <= 1:
        out, aux = local_layers(stacked_params, x, *extras)
        return (out, aux) if returns_aux else out

    V = virtual_stages
    M = n_micro or pp
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % (pp * V):
        raise ValueError(f"layers {L} not divisible by stages*virtual {pp}*{V}")
    if V > 1 and M < pp:
        raise ValueError(
            f"interleaved schedule needs n_micro >= stages ({M} < {pp})")
    cpu_f32 = _cpu_needs_f32(mesh, axis, manual_axes, x, stacked_params,
                             list(extras))
    out_dtype = x.dtype
    if cpu_f32:
        x = x.astype(jnp.float32)
        stacked_params = _upcast_tree(stacked_params)
        extras = tuple(_upcast_tree(list(extras)))
    mb = jnp.reshape(x, (M, B // M) + x.shape[1:])
    # (V, P, Lc, ...): chunk c = v*P + s holds consecutive layers, owned by
    # stage c % P — the interleaved round-robin assignment
    chunked = jax.tree.map(
        lambda a: jnp.reshape(a, (V, pp, L // (pp * V)) + a.shape[1:]),
        stacked_params)

    def pipe_local(stage_params, mbs, *ex):
        # manual over `axis` only: stage_params leaves arrive as (V, 1, Lc, ...)
        stage_params = jax.tree.map(lambda a: a[:, 0], stage_params)
        idx = jax.lax.axis_index(axis)
        fwd = [(i, (i + 1) % pp) for i in range(pp)]
        is_last = idx == pp - 1
        T = V * M + pp - 1

        def tick(carry, t):
            state, outs, wrap, aux_acc = carry
            r = t - idx                       # local step: chunk v, microbatch m
            valid = (r >= 0) & (r < V * M)
            v = jnp.clip(r // M, 0, V - 1)
            m = jnp.clip(r % M, 0, M - 1)
            inp = jax.lax.dynamic_index_in_dim(mbs, m, 0, keepdims=False)
            if V > 1:
                wrapped = jax.lax.dynamic_index_in_dim(wrap, m, 0, keepdims=False)
                inp = jnp.where(v == 0, inp, wrapped)
            h = jnp.where(idx == 0, inp, state)
            sp_v = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
                stage_params)
            y, a = local_layers(sp_v, h, *ex)
            aux_acc = aux_acc + jnp.where(valid, a, 0.0)
            # last stage, last chunk: collect final outputs
            done = valid & is_last & (v == V - 1)
            outs = jnp.where(
                done, jax.lax.dynamic_update_index_in_dim(outs, y, m, 0), outs)
            state = jax.lax.ppermute(jnp.where(valid, y, 0.0), axis, fwd)
            if V > 1:
                # stage 0 receives chunk v<V-1 outputs from stage P-1 and
                # queues them for the next round (the interleave wrap-around)
                r_send = (t + 1) - idx - pp   # sender's local step this arrival
                arr = ((idx == 0) & (r_send >= 0) & (r_send < V * M)
                       & (r_send // M < V - 1))
                m_send = jnp.clip(r_send % M, 0, M - 1)
                wrap = jnp.where(
                    arr,
                    jax.lax.dynamic_update_index_in_dim(wrap, state, m_send, 0),
                    wrap)
            return (state, outs, wrap, aux_acc), None

        vary = (axis,) + tuple(a for a in manual_axes if a != axis)
        state0 = _pcast_to(jnp.zeros_like(mbs[0]), vary)
        outs0 = _pcast_to(jnp.zeros_like(mbs), vary)
        # the wrap-around queue exists only for interleaved schedules — keep
        # the default GPipe scan free of the dead (M, ...) carry
        wrap0 = (_pcast_to(jnp.zeros_like(mbs), vary) if V > 1
                 else jnp.zeros((), mbs.dtype))
        aux0 = _pcast_to(jnp.float32(0.0), vary)
        (_, outs, _, aux_acc), _ = jax.lax.scan(
            tick, (state0, outs0, wrap0, aux0), jnp.arange(T))
        # broadcast the last stage's buffer to the whole pipe axis; aux is a
        # per-(stage, shard) partial sum — reduce over EVERY manual axis
        outs = jax.lax.psum(jnp.where(is_last, outs, 0.0), axis)
        for a in vary:
            aux_acc = jax.lax.psum(aux_acc, a)
        return outs, aux_acc

    # manual over `axis` (+ any requested manual_axes, e.g. 'sep' for ring
    # attention inside stages); every other mesh axis stays automatic, so
    # GSPMD still lays out TP/DP inside stages
    pspec = jax.tree.map(lambda _: P(None, axis), chunked)
    rep = P()
    mb_spec = P(None, *x_spec) if x_spec is not None else rep
    ex_specs = tuple(extras_specs) if extras_specs else tuple(rep for _ in extras)
    out, aux = shard_map(
        pipe_local, mesh=mesh,
        in_specs=(pspec, mb_spec) + ex_specs,
        # check_vma=True is REQUIRED for collectives under partial-manual
        # shard_map (vma tracking proves the psum'd output is pipe-invariant)
        out_specs=(mb_spec, P()), check_vma=True,
        axis_names=frozenset({axis}) | frozenset(manual_axes),
    )(chunked, mb, *extras)
    out = jnp.reshape(out, x.shape)
    if cpu_f32:  # only undo the harness upcast — a block_fn that widens
        out = out.astype(out_dtype)  # its output dtype keeps doing so
    return (out, aux) if returns_aux else out


# ---------------------------------------------------------------------------
# 1F1B train schedule — hand-rolled grads, ≤ P stashed microbatches per stage
# ---------------------------------------------------------------------------


def pipeline_1f1b(block_fn, head_fn, stacked_params, head_params, x, labels,
                  extras: Sequence[Any] = (), mesh: Optional[Mesh] = None,
                  axis: str = "pipe", n_micro: Optional[int] = None,
                  remat: bool = True, manual_axes: Sequence[str] = (),
                  x_spec: Optional[P] = None,
                  extras_specs: Optional[Sequence[P]] = None,
                  labels_spec: Optional[P] = None,
                  aux_scale: float = 0.0, returns_aux: bool = False):
    """One-forward-one-backward pipelined train step (reference 1F1B,
    pipeline_parallel.py:387), computed WITHOUT autodiff through the
    schedule: per-stage activation stash is a (P, ...) ring buffer — the
    1F1B in-flight bound — and the stage backward recomputes its forward
    from the stashed input (recompute-1F1B).

    block_fn(h, layer_params, *extras) -> h' (or (h', aux) if returns_aux)
    head_fn(y, head_params, labels_mb) -> scalar loss CONTRIBUTION of one
        microbatch (caller folds any 1/tokens normalization in).
    x: (B, ...) block-stack input (embeddings); labels: (B, ...) int labels.

    Returns (loss, aux_total, (d_stacked, d_head, dx)) — dx is the cotangent
    w.r.t. x (backprop it into the embedding outside).  Schedule length is
    2(M+P-1) ticks; per tick every stage runs one fused fwd(+head)+vjp, so
    it trades ~2x stage compute vs GPipe-by-AD for the O(P) memory bound.
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    pp = num_stages(mesh, axis) if mesh is not None else 1
    blk = _wrap_block(block_fn, returns_aux)
    if remat:
        blk = jax.checkpoint(blk)

    def local_layers(stage_params, h, *ex):
        def body(carry, lp):
            h, aux = carry
            h, a = blk(h, lp, *ex)
            return (h, _pcast_to(aux + a, _vma(h))), None
        aux0 = _pcast_to(jnp.float32(0.0), _vma(h))
        (out, aux), _ = jax.lax.scan(body, (h, aux0), stage_params)
        return out, aux

    if pp <= 1:
        def full(params, hp, h):
            y, aux = local_layers(params, h, *extras)
            return head_fn(y, hp, labels) + aux_scale * aux, aux
        loss, vjp, aux = jax.vjp(full, stacked_params, head_params, x,
                                 has_aux=True)
        dsp, dhp, dx = vjp(jnp.float32(1.0))
        return loss, aux, (dsp, dhp, dx)

    M = n_micro or pp
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if M < pp:
        raise ValueError(f"1F1B needs n_micro >= stages ({M} < {pp})")
    in_dtypes = None
    if _cpu_needs_f32(mesh, axis, manual_axes, x, stacked_params,
                      head_params, list(extras)):
        in_dtypes = (jax.tree.map(lambda a: a.dtype, stacked_params),
                     jax.tree.map(lambda a: a.dtype, head_params), x.dtype)
        x = x.astype(jnp.float32)
        stacked_params = _upcast_tree(stacked_params)
        head_params = _upcast_tree(head_params)
        extras = tuple(_upcast_tree(list(extras)))
    mb = jnp.reshape(x, (M, B // M) + x.shape[1:])
    lb = jnp.reshape(labels, (M, B // M) + labels.shape[1:])
    T = 2 * (M + pp - 1)

    def pipe_local(stage_params, hp, mbs, lbls, *ex):
        idx = jax.lax.axis_index(axis)
        is_last = idx == pp - 1
        w = pp - 1 - idx                       # warmup forwards at this stage
        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
        bwd_perm = [((i + 1) % pp, i) for i in range(pp)]

        vary_all = (axis,) + tuple(a for a in manual_axes if a != axis)
        # make hp device-varying up front: head grads are then computed
        # LOCALLY by the vjp (no implicit psum), which keeps the head's
        # lax.cond below legal — a psum inside a stage-divergent branch
        # would deadlock.  The explicit psum happens once, after the scan.
        hp_v = jax.tree.map(lambda a: _pcast_to(a, vary_all), hp)

        def stage_fwd(sp, h):
            y, aux = local_layers(sp, h, *ex)
            # pin outputs to the full varying set so the vjp cotangents
            # (which depend on the device-varying schedule) type-check
            return _pcast_to(y, vary_all), _pcast_to(aux, vary_all)

        def sched_F(stage, f):
            """Tick of the f-th forward at `stage` (Megatron 1F1B timing)."""
            ws = pp - 1 - stage
            return jnp.where(f < ws, stage + f, 2 * pp - 2 - stage + 2 * (f - ws))

        def tick(carry, u):
            (fcnt, bcnt, acnt, act_in, g_in, stash,
             gsp, ghp, loss_acc, aux_acc, dxb) = carry
            fwd_valid = (fcnt < M) & (u == sched_F(idx, fcnt))
            bwd_valid = (bcnt < M) & (u == 2 * pp - 1 - idx + 2 * bcnt)
            # arrivals: stage>0 receives exactly when stage-1 forwarded last
            # tick; stage 0 "receives" its own input microbatch at fwd ticks
            arr_valid = jnp.where(
                idx > 0,
                (acnt < M) & (u == sched_F(idx - 1, acnt) + 1),
                fwd_valid)
            arr_val = jnp.where(
                idx > 0, act_in,
                jax.lax.dynamic_index_in_dim(
                    mbs, jnp.clip(fcnt, 0, M - 1), 0, keepdims=False))
            slot_in = jnp.where(idx > 0, acnt, fcnt) % pp
            stash = jnp.where(
                arr_valid,
                jax.lax.dynamic_update_index_in_dim(stash, arr_val, slot_in, 0),
                stash)

            h_fwd = jax.lax.dynamic_index_in_dim(
                stash, fcnt % pp, 0, keepdims=False)
            h_bwd = jax.lax.dynamic_index_in_dim(
                stash, bcnt % pp, 0, keepdims=False)
            # fwd and bwd never fire on the same tick, so ONE fused
            # fwd(+head) + vjp serves both: fwd ticks use y, bwd ticks the grads
            h_sel = jnp.where(bwd_valid, h_bwd, h_fwd)
            lbl_sel = jax.lax.dynamic_index_in_dim(
                lbls, jnp.clip(bcnt, 0, M - 1), 0, keepdims=False)
            (y, aux), vjp = jax.vjp(stage_fwd, stage_params, h_sel)
            f32 = jnp.float32

            # the head (hidden->vocab projection + loss) only matters on the
            # LAST stage's backward ticks; a cond skips its cost everywhere
            # else (it is often the single largest matmul in the model)
            def _with_head(_):
                hl, hvjp = jax.vjp(
                    lambda yy, hpp: head_fn(yy, hpp, lbl_sel), y, hp_v)
                dy, dhp = hvjp(_pcast_to(f32(1.0), vary_all))
                return hl, dy, dhp

            def _no_head(_):
                return (_pcast_to(f32(0.0), vary_all),
                        _pcast_to(jnp.zeros_like(y), vary_all),
                        jax.tree.map(
                            lambda a: _pcast_to(jnp.zeros_like(a), vary_all),
                            hp_v))

            hl, dy_head, dhp = jax.lax.cond(
                bwd_valid & is_last, _with_head, _no_head, None)

            cot_y = _pcast_to(
                jnp.where(bwd_valid, jnp.where(is_last, dy_head, g_in), 0.0),
                vary_all)
            cot_aux = _pcast_to(
                jnp.where(bwd_valid, f32(aux_scale), f32(0.0)), vary_all)
            dsp, dh = vjp((cot_y, cot_aux))
            # masked cotangents already zero the grads on non-bwd ticks
            gsp = jax.tree.map(jnp.add, gsp, dsp)
            ghp = jax.tree.map(jnp.add, ghp, dhp)
            loss_acc = loss_acc + hl  # zero off the last stage's bwd ticks
            aux_acc = aux_acc + jnp.where(bwd_valid, aux, 0.0)
            dxb = jnp.where(
                bwd_valid & (idx == 0),
                jax.lax.dynamic_update_index_in_dim(
                    dxb, dh, jnp.clip(bcnt, 0, M - 1), 0),
                dxb)
            act_in = jax.lax.ppermute(jnp.where(fwd_valid, y, 0.0), axis, fwd_perm)
            g_in = jax.lax.ppermute(jnp.where(bwd_valid, dh, 0.0), axis, bwd_perm)
            return (fcnt + fwd_valid, bcnt + bwd_valid, acnt + arr_valid,
                    act_in, g_in, stash, gsp, ghp, loss_acc, aux_acc, dxb), None

        pc = functools.partial(_pcast_to, vary=vary_all)
        i32 = jnp.int32
        stash0 = pc(jnp.zeros((pp,) + mbs.shape[1:], mbs.dtype))
        carry0 = (pc(i32(0)), pc(i32(0)), pc(i32(0)),
                  pc(jnp.zeros_like(mbs[0])), pc(jnp.zeros_like(mbs[0])),
                  stash0,
                  jax.tree.map(lambda a: pc(jnp.zeros_like(a)), stage_params),
                  # ghp accumulates LOCAL (varying) head grads — hp was pcast
                  # to varying so the cond'd head vjp never psums; the
                  # explicit reduction happens after the scan
                  jax.tree.map(lambda a: pc(jnp.zeros_like(a)), hp),
                  pc(jnp.float32(0.0)), pc(jnp.float32(0.0)),
                  pc(jnp.zeros_like(mbs)))
        (_, _, _, _, _, _, gsp, ghp, loss_acc, aux_acc, dxb), _ = jax.lax.scan(
            tick, carry0, jnp.arange(T))

        # NB on reductions: stage_params enter this manual region INVARIANT
        # over the non-pipe manual axes, and vma-aware AD already psums the
        # cotangent of an invariant input over those axes — gsp comes out of
        # the vjp reduced over them (and stays per-stage over pipe, as its
        # P(axis) out_spec requires).  hp was explicitly pcast to varying, so
        # its grads ARE local and need the full psum here, as do the primal
        # accumulators (loss, aux) and the stage-0-owned dx buffer.
        red = [axis] + [a for a in manual_axes if a != axis]
        loss = loss_acc
        aux = aux_acc
        for a in red:
            loss = jax.lax.psum(loss, a)
            aux = jax.lax.psum(aux, a)
            ghp = jax.tree.map(lambda g, a=a: jax.lax.psum(g, a), ghp)
        dxb = jax.lax.psum(jnp.where(idx == 0, dxb, 0.0), axis)
        return loss, aux, gsp, ghp, dxb

    pspec = _stage_param_specs(stacked_params, axis)
    rep = P()
    hspec = jax.tree.map(lambda _: rep, head_params)
    mb_spec = P(None, *x_spec) if x_spec is not None else rep
    lb_spec = P(None, *labels_spec) if labels_spec is not None else rep
    ex_specs = tuple(extras_specs) if extras_specs else tuple(rep for _ in extras)
    loss, aux, gsp, ghp, dxb = shard_map(
        pipe_local, mesh=mesh,
        in_specs=(pspec, hspec, mb_spec, lb_spec) + ex_specs,
        out_specs=(P(), P(), pspec, hspec, mb_spec), check_vma=True,
        axis_names=frozenset({axis}) | frozenset(manual_axes),
    )(stacked_params, head_params, mb, lb, *extras)
    dx = jnp.reshape(dxb, x.shape)
    if in_dtypes is not None:  # cpu-f32 harness: grads back to param dtypes
        sp_dt, hp_dt, x_dt = in_dtypes
        gsp = jax.tree.map(lambda g, d: g.astype(d), gsp, sp_dt)
        ghp = jax.tree.map(lambda g, d: g.astype(d), ghp, hp_dt)
        dx = dx.astype(x_dt)
    return loss + aux_scale * aux, aux, (gsp, ghp, dx)
