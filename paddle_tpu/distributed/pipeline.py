"""Pipeline parallelism over the `pipe` mesh axis — single-jit SPMD schedule.

Reference analog: fleet.meta_parallel.PipelineParallel
(fleet/meta_parallel/pipeline_parallel.py:132, 1F1B at :387, interleaved at
:822,1016) and the P2P layer (pp_utils/p2p_communication.py:302) built on
NCCL batch_isend_irecv.  The TPU has no NCCL p2p; the idiomatic design
(SURVEY.md §7 "Hard parts") runs the WHOLE microbatch schedule inside one jit:

  * layer-stacked params are sharded over `pipe` (each stage owns L/P layers),
  * activations move stage-to-stage with `jax.lax.ppermute` (neighbor ICI hop),
  * a `lax.scan` shift-register executes M + P - 1 ticks (GPipe-style fill/
    drain; XLA overlaps the ppermute with the next tick's compute),
  * `shard_map` is MANUAL only over `pipe` — every other axis stays `auto`,
    so tensor/sequence/data sharding inside a stage is still pure GSPMD.

Backward is just `jax.grad` through the scan: the transpose of ppermute is the
reverse rotation, so AD materializes the reverse schedule automatically — the
1F1B runtime the reference hand-codes in Python falls out of the autodiff.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib


def _stage_param_specs(stacked_params, axis: str):
    """P(axis) on the leading (layer) dim of every leaf."""
    return jax.tree.map(lambda _: P(axis), stacked_params)


def num_stages(mesh: Mesh, axis: str = "pipe") -> int:
    return mesh.shape.get(axis, 1) if axis in mesh.axis_names else 1


def pipeline_apply(block_fn, stacked_params, x, extras: Sequence[Any] = (),
                   mesh: Optional[Mesh] = None, axis: str = "pipe",
                   n_micro: Optional[int] = None, remat: bool = True,
                   manual_axes: Sequence[str] = (),
                   x_spec: Optional[P] = None,
                   extras_specs: Optional[Sequence[P]] = None):
    """Run `x` through L stacked layers, pipelined over the `axis` mesh axis.

    block_fn(h, layer_params, *extras) -> h'   (one transformer block)
    stacked_params: pytree with leading layer dim L on every leaf (L % P == 0)
    x: (B, ...) activations; microbatched along B (B % n_micro == 0)
    extras: replicated side inputs (rope tables, masks, ...)

    manual_axes: additional mesh axes to make manual inside the stage body —
    used to compose with ring/Ulysses attention, whose `sep` collectives must
    see a manual axis.  When set, x_spec (spec of x WITHOUT the microbatch
    dim, e.g. P(None, 'sep', None) for seq-sharded activations) and
    extras_specs describe how those inputs are sharded over the manual axes.

    Returns activations shaped like x.  With no live pipe axis this reduces to
    a plain lax.scan over layers.
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    pp = num_stages(mesh, axis) if mesh is not None else 1

    if remat:
        block_fn = jax.checkpoint(block_fn)

    def local_layers(stage_params, h, *ex):
        def body(carry, lp):
            return block_fn(carry, lp, *ex), None
        out, _ = jax.lax.scan(body, h, stage_params)
        return out

    if pp <= 1:
        return local_layers(stacked_params, x, *extras)

    M = n_micro or pp
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = jnp.reshape(x, (M, B // M) + x.shape[1:])

    def pipe_local(stage_params, mbs, *ex):
        # manual over `axis` only: stage_params leaves arrive as (L/P, ...)
        idx = jax.lax.axis_index(axis)
        fwd = [(i, (i + 1) % pp) for i in range(pp)]
        is_last = idx == pp - 1

        def tick(carry, t):
            state, outs = carry
            inp = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            h = jnp.where(idx == 0, inp, state)
            y = local_layers(stage_params, h, *ex)
            oi = t - (pp - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, y, jnp.clip(oi, 0, M - 1), 0)
            outs = jnp.where((oi >= 0) & is_last, upd, outs)
            state = jax.lax.ppermute(y, axis, fwd)
            return (state, outs), None

        # mark the carries varying over every manual axis (vma scan typing);
        # seq-sharded inputs are already sep-varying, so only cast the rest
        vary = (axis,) + tuple(a for a in manual_axes if a != axis)

        def pcast_to(val):
            cur = getattr(jax.typeof(val), "vma", frozenset())
            need = tuple(a for a in vary if a not in cur)
            return jax.lax.pcast(val, need, to="varying") if need else val

        state0 = pcast_to(jnp.zeros_like(mbs[0]))
        outs0 = pcast_to(jnp.zeros_like(mbs))
        (_, outs), _ = jax.lax.scan(
            tick, (state0, outs0), jnp.arange(M + pp - 1))
        # broadcast the last stage's buffer to the whole pipe axis
        return jax.lax.psum(jnp.where(is_last, outs, 0.0), axis)

    # manual over `axis` (+ any requested manual_axes, e.g. 'sep' for ring
    # attention inside stages); every other mesh axis stays automatic, so
    # GSPMD still lays out TP/DP inside stages
    pspec = _stage_param_specs(stacked_params, axis)
    rep = P()
    mb_spec = P(None, *x_spec) if x_spec is not None else rep
    ex_specs = tuple(extras_specs) if extras_specs else tuple(rep for _ in extras)
    out = shard_map(
        pipe_local, mesh=mesh,
        in_specs=(pspec, mb_spec) + ex_specs,
        # check_vma=True is REQUIRED for collectives under partial-manual
        # shard_map (vma tracking proves the psum'd output is pipe-invariant)
        out_specs=mb_spec, check_vma=True,
        axis_names=frozenset({axis}) | frozenset(manual_axes),
    )(stacked_params, mb, *extras)
    return jnp.reshape(out, x.shape)
