"""`shard_map` import shim.

Newer jax exposes `jax.shard_map` (kwargs: check_vma, axis_names); older
releases ship `jax.experimental.shard_map.shard_map` (kwargs: check_rep,
auto).  Call sites in this package use the new spelling; on older jax this
module adapts: check_vma -> check_rep, axis_names (the MANUAL axes) ->
auto (every other mesh axis).
"""

from __future__ import annotations

try:  # jax >= 0.6
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                  axis_names=None, **kw):
        import jax as _jax

        auto = frozenset()
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
        if check_vma is not None:
            # partial-auto + check_rep is unsupported on legacy jax; without
            # auto the flag maps straight through
            kw["check_rep"] = False if auto else check_vma
        mapped = _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, **kw)
        if auto:
            # legacy _shard_map_impl raises NotImplementedError for partial
            # auto when called EAGERLY; the jit partitioning path supports it
            mapped = _jax.jit(mapped)
        return mapped

try:  # jax >= 0.6: avals carry the vma (varying-manual-axes) set
    from jax import typeof
except ImportError:
    def typeof(x):
        """Older jax has no jax.typeof and no vma tracking; callers read
        `.vma` via getattr-with-default, so the plain aval is the right
        no-op stand-in."""
        import jax.core
        return jax.core.get_aval(x)

__all__ = ["shard_map", "typeof"]
