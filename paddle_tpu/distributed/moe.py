"""Mixture-of-Experts with expert parallelism — TPU-native (C29).

Reference parity: `incubate/distributed/models/moe/moe_layer.py:263 MoELayer`
(all-to-all dispatch at :107-156), gates under `moe/gate/` (gshard_gate.py,
switch_gate.py, naive_gate.py), and the `global_scatter`/`global_gather` ops
(`distributed/utils/moe_utils.py:20,146`).

TPU-native design (SURVEY.md §7 step 5):
  - Experts are STACKED on a leading axis of the expert weights, sharded over
    the mesh's ``expert`` axis.  Token dispatch is the GShard einsum form:
    ``dispatch (N, X, C) x tokens (N, E) -> (X, C, E)``.  When tokens are
    batch-sharded and experts expert-sharded, XLA lowers that einsum to the
    all-to-all the reference implements by hand with global_scatter — no
    manual comm code on the hot path.
  - Gating (top-1 switch / top-2 gshard) is dense one-hot math: no sorting,
    no dynamic shapes — everything tiles onto the MXU/VPU.
  - Capacity-factor token dropping, load-balance aux loss (GShard eq.(4)),
    router z-loss (ST-MoE) are all fused into the gating computation.
  - `global_scatter`/`global_gather` are also provided explicitly (shard_map +
    lax.all_to_all over the expert axis) for API parity and for users who
    want manual expert parallelism.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._shard_map_compat import shard_map

from ..nn.layer import Layer as _Layer


# ---------------------------------------------------------------------------
# Gating
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int
    top_k: int = 2                      # 1 = switch, 2 = gshard
    # C = ceil(k*N/X * factor); None = drop-free (C = N, NaiveGate semantics)
    capacity_factor: Optional[float] = 1.25
    min_capacity: int = 4
    aux_loss_weight: float = 0.01       # GShard load-balance loss weight
    z_loss_weight: float = 1e-3         # router logit z-loss (ST-MoE)
    normalize_top_k: bool = True        # renormalize top-k gate weights
    gate_dtype: Any = jnp.float32
    # "einsum" | "scatter" | "gmm" | None (auto: "gmm" when capacity_factor
    # is None — dropless needs no capacity buffers at all — else scatter
    # once the one-hot dispatch tensor would exceed _EINSUM_DISPATCH_LIMIT
    # bytes)
    dispatch_mode: Optional[str] = None


def compute_capacity(num_tokens: int, cfg: MoEConfig) -> int:
    if cfg.capacity_factor is None:
        # drop-free: a token occupies at most one slot per expert (top-k picks
        # are distinct experts), so N slots per expert covers the worst case.
        # The einsum path's dispatch/combine are then (N, X, N) — O(N^2 X)
        # memory; auto dispatch routes capacity_factor=None to the gmm mode,
        # which needs no capacity buffers at all.
        return num_tokens
    cap = int(np.ceil(cfg.top_k * num_tokens / cfg.num_experts
                      * cfg.capacity_factor))
    # a token occupies at most one slot per expert, so capacity beyond N
    # buys nothing: clamp keeps large capacity_factor configs from
    # allocating (N, X, C>N) dispatch tensors bigger than N ever fills
    return min(max(cap, cfg.min_capacity), num_tokens)


def gating_indices(logits, cfg: MoEConfig, capacity: Optional[int] = None,
                   need_positions: bool = True):
    """Index-form GShard/Switch gating — the single source of routing truth.

    logits: (N, X) float.  Returns (expert_idx (N, k) int32, pos (N, k) int32
    position within the expert's capacity buffer, keep (N, k) 0/1 float,
    gate_vals (N, k) float, aux_loss scalar, C).

    Position-in-expert is a cumsum over one-hot masks (static shapes, no
    sort), slot-major priority — all slot-0 picks rank before any slot-1
    pick, matching GShard's "top-1 tokens first" drop policy.  Memory is
    O(N·X): nothing of size C is materialized here, which is what lets the
    scatter dispatch below scale past the one-hot form's N·X·C wall
    (reference hits the same wall differently: its all-to-all buffers are
    count-sized, moe_utils.py:20).

    need_positions=False skips the cumsum subgraph entirely and returns
    trivial pos (zeros) / keep (ones): the dropless gmm dispatch neither
    drops tokens nor uses capacity slots, and the Graph Doctor
    (paddle_tpu.analysis, DEAD_CODE) showed the k one_hot+cumsum chains
    being traced dead on every gmm step.
    """
    N, X = logits.shape
    C = capacity if capacity is not None else compute_capacity(N, cfg)
    logits = logits.astype(cfg.gate_dtype)
    probs = jax.nn.softmax(logits, axis=-1)                    # (N, X)

    gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)    # (N, k)
    if cfg.normalize_top_k and cfg.top_k > 1:
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

    if need_positions:
        counts = jnp.zeros((X,), cfg.gate_dtype)
        poss, keeps = [], []
        for j in range(cfg.top_k):
            m = jax.nn.one_hot(expert_idx[:, j], X,
                               dtype=cfg.gate_dtype)                   # (N, X)
            pos = jnp.cumsum(m, axis=0) - 1.0 + counts[None, :]        # (N, X)
            counts = counts + m.sum(axis=0)
            poss.append((pos * m).sum(-1).astype(jnp.int32))
            keeps.append(((pos < C) * m).sum(-1).astype(cfg.gate_dtype))
        pos = jnp.stack(poss, axis=1)                          # (N, k)
        keep = jnp.stack(keeps, axis=1)                        # (N, k)
    else:
        pos = jnp.zeros_like(expert_idx)
        keep = jnp.ones(expert_idx.shape, cfg.gate_dtype)

    # GShard eq.(4) load-balance loss: X * sum_x f_x * p_x where f_x is the
    # fraction of tokens whose TOP-1 pick is x and p_x the mean router prob.
    top1 = jax.nn.one_hot(expert_idx[:, 0], X, dtype=cfg.gate_dtype)
    f = top1.mean(axis=0)
    p = probs.mean(axis=0)
    aux = cfg.aux_loss_weight * X * jnp.sum(f * p)
    if cfg.z_loss_weight:
        z = jax.nn.logsumexp(logits, axis=-1)
        aux = aux + cfg.z_loss_weight * jnp.mean(z * z)
    return expert_idx.astype(jnp.int32), pos, keep, gate_vals, aux, C


def routing_metrics(keep, top_k: int):
    """Aux metrics from a (N, k) keep mask: how many (token, slot) picks
    the capacity buffers actually admitted.  The capacity-based modes used
    to drop overflow tokens silently; `dropped_fraction` makes the loss
    visible (bench.py reports it in the moe extra dict)."""
    routed = jnp.float32(keep.shape[0] * top_k)
    kept = keep.astype(jnp.float32).sum()
    return {
        "dropped_count": routed - kept,
        "routed_count": routed,
        "dropped_fraction": (routed - kept) / jnp.maximum(routed, 1.0),
    }


def _one_hot_dispatch(expert_idx, pos, keep, gate_vals, X: int, C: int,
                      dtype):
    """(N, X, C) dispatch/combine one-hots from `gating_indices` outputs —
    the single construction both `top_k_gating` and the einsum moe_ffn
    branch share (parity depends on there being exactly one copy)."""
    N, k = expert_idx.shape
    dispatch = jnp.zeros((N, X, C), dtype)
    combine = jnp.zeros((N, X, C), dtype)
    for j in range(k):
        d = (keep[:, j, None, None]
             * jax.nn.one_hot(expert_idx[:, j], X, dtype=dtype)[:, :, None]
             * jax.nn.one_hot(pos[:, j], C, dtype=dtype)[:, None, :])
        dispatch = dispatch + d
        combine = combine + gate_vals[:, j][:, None, None] * d
    return dispatch, combine


def top_k_gating(logits, cfg: MoEConfig, capacity: Optional[int] = None,
                 return_metrics: bool = False):
    """One-hot GShard/Switch gating (reference gshard_gate.py/switch_gate.py).

    logits: (N, X) float. Returns (dispatch (N, X, C) bool-ish float,
    combine (N, X, C) float, aux_loss scalar[, metrics dict when
    `return_metrics` — see `routing_metrics`]).  Built from
    `gating_indices` so both dispatch forms share one routing decision.
    """
    N, X = logits.shape
    expert_idx, pos, keep, gate_vals, aux, C = gating_indices(
        logits, cfg, capacity)
    dispatch, combine = _one_hot_dispatch(expert_idx, pos, keep, gate_vals,
                                          X, C, cfg.gate_dtype)
    if return_metrics:
        return dispatch, combine, aux, routing_metrics(keep, cfg.top_k)
    return dispatch, combine, aux


# ---------------------------------------------------------------------------
# Functional MoE FFN (the hot path used by models)
# ---------------------------------------------------------------------------


def init_moe_ffn_params(key, hidden: int, intermediate: int, cfg: MoEConfig,
                        dtype=jnp.bfloat16, std: float = 0.02):
    """Expert weights stacked on a leading (X,) axis + router. SwiGLU experts."""
    X, E, F = cfg.num_experts, hidden, intermediate
    ks = jax.random.split(key, 4)
    n = lambda k, s: (std * jax.random.normal(k, s, jnp.float32)).astype(dtype)
    return {
        "router": (std * jax.random.normal(ks[0], (E, X), jnp.float32)),
        "w_gate": n(ks[1], (X, E, F)),
        "w_up": n(ks[2], (X, E, F)),
        "w_down": n(ks[3], (X, F, E)),
    }


def moe_ffn_logical_axes():
    """Logical sharding axes (mesh.LOGICAL_RULES maps expert->expert axis,
    mlp->model axis: expert parallel composes with tensor parallel)."""
    return {
        "router": (None, None),
        "w_gate": ("expert", "embed", "mlp"),
        "w_up": ("expert", "embed", "mlp"),
        "w_down": ("expert", "mlp", "embed"),
    }


# above this many bytes of one-hot dispatch tensor, auto mode switches to
# the scatter dispatch (the 16G-HBM v5e hits the wall around 8k tokens with
# X=8: at N=16k, C=5120 each of dispatch+combine is N·X·C·4B ~ 2.7G, ~5.4G
# for the pair)
_EINSUM_DISPATCH_LIMIT = 64 * 1024 * 1024


def _expert_ffn(xp, p):
    """SwiGLU over stacked expert buffers xp (X, C, E) -> (X, C, E)."""
    g = jnp.einsum("xce,xef->xcf", xp, p["w_gate"])
    u = jnp.einsum("xce,xef->xcf", xp, p["w_up"])
    h = (jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u)
    return jnp.einsum("xcf,xfe->xce", h, p["w_down"])


def _gmm_expert_ffn(tok, p, cfg: MoEConfig, expert_idx, gate_vals):
    """Dropless expert FFN via the Pallas grouped matmul.

    tok: (N, E); expert_idx/gate_vals: (N, k) from `gating_indices`.  The
    (token, slot) pairs are stably sorted by destination expert, scattered
    into the kernel's tile-aligned layout (`make_layout`), run through
    three GMMs (SwiGLU), and gathered back — compute scales with actual
    tokens per expert, nothing is dropped.
    """
    from ..kernels import pallas_grouped_matmul as pgmm

    N, E = tok.shape
    k = cfg.top_k
    X = cfg.num_experts
    eflat = expert_idx.reshape(N * k)
    # stable argsort: tokens within an expert stay in (token, slot) order
    order = jnp.argsort(eflat, stable=True)                    # (N*k,)
    group_sizes = jnp.zeros((X,), jnp.int32).at[eflat].add(1)
    offs = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes)])
    layout = pgmm.make_layout(group_sizes, N * k)
    g_sorted = eflat[order]
    rank = jnp.arange(N * k, dtype=jnp.int32) - offs[g_sorted]
    dest = layout.starts[g_sorted] + rank                      # (N*k,)
    x_pad = jnp.zeros((layout.padded_rows, E), tok.dtype).at[dest].set(
        tok[order // k], unique_indices=True)

    run = functools.partial(pgmm.gmm, group_sizes=group_sizes,
                            padded_rows=layout.padded_rows,
                            tile_m=layout.tile_m)
    g = run(x_pad, p["w_gate"])
    u = run(x_pad, p["w_up"])
    h = (jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u)
    o_pad = run(h, p["w_down"])

    y_sorted = o_pad[dest]                                     # (N*k, E)
    y = jnp.zeros_like(y_sorted).at[order].set(y_sorted,
                                               unique_indices=True)
    w = gate_vals.astype(tok.dtype)[..., None]                 # (N, k, 1)
    return (y.reshape(N, k, E) * w).sum(axis=1)


def moe_ffn(x, p, cfg: MoEConfig, dispatch: Optional[str] = None,
            return_metrics: bool = False):
    """MoE SwiGLU FFN.  x: (B, S, E) -> (out (B, S, E), aux_loss[,
    metrics dict when `return_metrics` — see `routing_metrics`]).

    Three dispatch forms sharing one routing decision (`gating_indices`):

    * "einsum" — GShard one-hot form.  The dispatch/combine einsums ARE the
      reference's global_scatter -> expert FFN -> global_gather pipeline
      (moe_layer.py:107-156): under GSPMD, with x batch-sharded and weights
      expert-sharded, XLA inserts the all-to-alls.  Costs O(N·X·C) memory
      and MACs for the routing itself.
    * "scatter" — index form: tokens scatter-add straight into the (X, C, E)
      expert buffers and gather back out, O(k·N·E) routing cost and no
      (N, X, C) tensor at all — this is what removes the reference's (and
      round-4's) single-chip token ceiling.
    * "gmm" — DROPLESS: tokens sort by destination expert and the expert
      FFN runs as a ragged Pallas grouped matmul
      (kernels/pallas_grouped_matmul.py).  No capacity buffers, no
      capacity padding, no token dropping; compute scales with the actual
      per-expert load.  Capacity settings are ignored.

    einsum/scatter are parity-pinned in tests (identical routing, drops
    and numerics); gmm matches them token-exactly whenever capacity drops
    nothing.  Auto mode picks gmm when `capacity_factor is None` (the
    dropless contract), else scatter once the one-hot tensors would exceed
    _EINSUM_DISPATCH_LIMIT bytes.
    """
    B, S, E = x.shape
    N = B * S
    X = cfg.num_experts
    tok = x.reshape(N, E)
    logits = tok.astype(cfg.gate_dtype) @ p["router"]
    mode = dispatch or cfg.dispatch_mode
    if mode is None:
        if cfg.capacity_factor is None:
            mode = "gmm"
        else:
            C = compute_capacity(N, cfg)
            onehot_bytes = 2 * N * X * C * jnp.dtype(cfg.gate_dtype).itemsize
            mode = ("scatter" if onehot_bytes > _EINSUM_DISPATCH_LIMIT
                    else "einsum")
    # gmm is dropless: skip tracing the capacity-position subgraph it
    # would never read (flagged by the Graph Doctor as dead code)
    e, pos, keep, gates, aux, C = gating_indices(
        logits, cfg, need_positions=(mode != "gmm"))
    if mode == "einsum":
        dispatch_t, combine = _one_hot_dispatch(e, pos, keep, gates, X, C,
                                                cfg.gate_dtype)
        xp = jnp.einsum("nxc,ne->xce", dispatch_t.astype(x.dtype),
                        tok)                                   # all-to-all in
        eo = _expert_ffn(xp, p)
        out = jnp.einsum("nxc,xce->ne", combine.astype(x.dtype), eo)
    elif mode == "scatter":
        vals = (keep[..., None] * tok[:, None, :]).astype(x.dtype)  # (N, k, E)
        # every kept (token, slot) owns a distinct (expert, pos) cell; drops
        # have pos >= C and fall out of bounds -> dropped by scatter mode
        xp = jnp.zeros((X, C, E), x.dtype).at[e, pos].add(
            vals, mode="drop", unique_indices=True)
        eo = _expert_ffn(xp, p)
        gath = eo[e, jnp.minimum(pos, C - 1)]                  # (N, k, E)
        w = (gates * keep).astype(x.dtype)[..., None]
        out = (gath * w).sum(axis=1)
    elif mode == "gmm":
        out = _gmm_expert_ffn(tok, p, cfg, e, gates)
        # keep is already all-ones (need_positions=False): dropless
    else:
        raise ValueError(f"unknown dispatch mode {mode!r} "
                         "(expected 'einsum', 'scatter' or 'gmm')")
    if return_metrics:
        return out.reshape(B, S, E), aux, routing_metrics(keep, cfg.top_k)
    return out.reshape(B, S, E), aux


# ---------------------------------------------------------------------------
# Explicit expert-parallel primitives (reference moe_utils.py parity)
# ---------------------------------------------------------------------------


def global_scatter(x, local_count=None, global_count=None, *, mesh: Mesh,
                   axis: str = "expert"):
    """Reference `global_scatter` (moe_utils.py:20): every rank holds its own
    tokens bucketed by destination expert; the exchange hands each expert's
    buckets to the rank that owns that expert.

    x: (R, X, C, ...) global — dim0 = source rank (sharded over `axis`),
    dim1 = all X experts, dim2 = per-rank capacity.  Returns
    (R, X//R, C*R, ...): each rank now owns X//R experts with the capacity
    blocks of all R source ranks concatenated.  counts args are accepted for
    API parity; the TPU form is dense/static so they are unused.
    """
    del local_count, global_count
    n = mesh.shape[axis]
    if x.shape[0] != n or x.shape[1] % n:
        raise ValueError(
            f"global_scatter expects x.shape[0] == mesh['{axis}'] size ({n}) "
            f"and experts dim divisible by it; got {x.shape}")

    def f(b):
        b = b[0]  # (X, C, ...)
        out = jax.lax.all_to_all(b, axis, split_axis=0, concat_axis=1,
                                 tiled=True)
        return out[None]

    spec = P(axis, *([None] * (x.ndim - 1)))
    return shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec)(x)


def global_gather(x, local_count=None, global_count=None, *, mesh: Mesh,
                  axis: str = "expert"):
    """Inverse of global_scatter (moe_utils.py:146): (R, X//R, C*R, ...) ->
    (R, X, C, ...) — expert outputs return to the token-owning ranks."""
    del local_count, global_count

    def f(b):
        b = b[0]
        out = jax.lax.all_to_all(b, axis, split_axis=1, concat_axis=0,
                                 tiled=True)
        return out[None]

    spec = P(axis, *([None] * (x.ndim - 1)))
    return shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec)(x)


# ---------------------------------------------------------------------------
# Eager MoELayer (paddle.incubate.distributed.models.moe.MoELayer parity)
# ---------------------------------------------------------------------------


class NaiveGate:
    """Plain top-k softmax gate, no capacity drop (naive_gate.py parity)."""

    def __init__(self, d_model, num_experts, top_k=2):
        self.cfg = MoEConfig(num_experts=num_experts, top_k=top_k,
                             capacity_factor=None, aux_loss_weight=0.0,
                             z_loss_weight=0.0)


class SwitchGate:
    """Top-1 gate with capacity (switch_gate.py parity)."""

    def __init__(self, d_model, num_experts, capacity_factor=1.25):
        self.cfg = MoEConfig(num_experts=num_experts, top_k=1,
                             capacity_factor=capacity_factor)


class GShardGate:
    """Top-2 gate with capacity + balance loss (gshard_gate.py parity)."""

    def __init__(self, d_model, num_experts, capacity_factor=1.25):
        self.cfg = MoEConfig(num_experts=num_experts, top_k=2,
                             capacity_factor=capacity_factor)


class MoELayer(_Layer):
    """Eager-API MoE layer over nn.Layer experts (moe_layer.py:263 parity).

    A real nn.Layer: the router is a registered Parameter, the experts are
    registered sublayers, and the whole dispatch -> expert -> combine path is
    built from tape-recorded ops (tensor.apply_op), so `loss.backward()`
    reaches router and expert weights.  `gate` is one of NaiveGate/SwitchGate/
    GShardGate or an MoEConfig.  `last_aux_loss` is a differentiable Tensor —
    add it to the training loss.  `last_dropped_fraction` reports the
    (token, slot) picks the capacity buffers rejected on the last forward.
    """

    def __init__(self, d_model, experts, gate=None, name=None):
        from ..nn.layer import LayerList
        from ..nn import initializer as I

        super().__init__(name_scope=name)
        self.d_model = d_model
        self.experts = LayerList(list(experts))
        cfg = gate.cfg if hasattr(gate, "cfg") else gate
        self.cfg = cfg or MoEConfig(num_experts=len(self.experts))
        if self.cfg.num_experts != len(self.experts):
            raise ValueError("gate num_experts != len(experts)")
        self.router = self.create_parameter(
            [d_model, self.cfg.num_experts],
            default_initializer=I.Normal(std=0.02))
        self.last_aux_loss = None
        self.last_dropped_fraction = None

    def forward(self, x):
        from .. import ops
        from ..tensor import apply_op, to_tensor

        x = to_tensor(x) if not hasattr(x, "_data") else x
        B, S, E = x.shape
        N = B * S
        tok = ops.reshape(x, [N, E])
        cfg = self.cfg

        def gating(tok_raw, router_raw):
            logits = tok_raw.astype(cfg.gate_dtype) @ router_raw
            return top_k_gating(logits, cfg)

        dispatch, combine, aux = apply_op("moe_gating", gating, tok, self.router)
        xp = apply_op(
            "moe_dispatch",
            lambda d, t: jnp.einsum("nxc,ne->xce", d.astype(t.dtype), t),
            dispatch, tok)
        eo = ops.stack([expert(xp[i]) for i, expert in enumerate(self.experts)],
                       axis=0)
        out = apply_op(
            "moe_combine",
            lambda c, e: jnp.einsum("nxc,xce->ne", c.astype(e.dtype), e),
            combine, eo)
        self.last_aux_loss = aux
        # dispatch.sum() counts admitted (token, slot) picks out of N*k
        self.last_dropped_fraction = apply_op(
            "moe_drop_stats",
            lambda d: 1.0 - d.sum() / jnp.float32(N * cfg.top_k), dispatch)
        return ops.reshape(out, [B, S, E])
