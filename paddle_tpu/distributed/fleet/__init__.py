"""paddle.distributed.fleet parity — hybrid-parallel facade over one Mesh.

Reference: fleet/fleet.py:99 Fleet (init:169), fleet/base/topology.py:60
CommunicateTopology / :173 HybridCommunicateGroup,
fleet/base/distributed_strategy.py:121 DistributedStrategy.

TPU-native: fleet.init builds ONE jax Mesh from the hybrid_configs degrees and
installs it as the global mesh; the per-axis "communication groups" of the
reference become views over mesh axes (collective.Group).  distributed_model /
distributed_optimizer don't wrap with reducers/hooks — data/grad placement is
GSPMD sharding, so they return annotation helpers instead.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .. import mesh as mesh_lib
from ..collective import Group
from ...optimizer.functional import AdamW

__all__ = ["DistributedStrategy", "CommunicateTopology", "HybridCommunicateGroup",
           "init", "get_hybrid_communicate_group", "distributed_model",
           "distributed_optimizer", "worker_num", "worker_index"]


class DistributedStrategy:
    """Knob bag (reference backs this with distributed_strategy.proto)."""

    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs: Dict[str, Any] = {}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {}
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1}
        self.find_unused_parameters = False
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {}

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class CommunicateTopology:
    """Reference topology.py:60 — axis-name -> degree cartesian topology."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._names = list(hybrid_group_names or
                           ["data", "pipe", "sharding", "sep", "model"])
        self._dims = list(dims or [1] * len(self._names))

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coords = [kwargs[n] for n in self._names]
        return int(np.ravel_multi_index(coords, self._dims))

    def get_coord(self, rank):
        return dict(zip(self._names, np.unravel_index(rank, self._dims)))


class HybridCommunicateGroup:
    """Reference topology.py:173 — per-axis group accessors over the mesh."""

    def __init__(self, topology: CommunicateTopology, mesh=None):
        self._topo = topology
        self._mesh = mesh

    def _axis_group(self, axis: str) -> Optional[Group]:
        if self._mesh is not None and axis in self._mesh.axis_names:
            return Group(mesh=self._mesh, axis=axis)
        return None

    def topology(self):
        return self._topo

    # degrees
    def get_data_parallel_world_size(self):
        return self._topo.get_dim("data")

    def get_model_parallel_world_size(self):
        return self._topo.get_dim("model")

    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pipe")

    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sep_parallel_world_size(self):
        return self._topo.get_dim("sep")

    # groups (mesh-axis views)
    def get_data_parallel_group(self):
        return self._axis_group("data")

    def get_model_parallel_group(self):
        return self._axis_group("model")

    def get_pipe_parallel_group(self):
        return self._axis_group("pipe")

    def get_sharding_parallel_group(self):
        return self._axis_group("sharding")

    def get_sep_parallel_group(self):
        return self._axis_group("sep")

    # single-controller: this process sees the whole mesh; rank-in-group is a
    # per-shard notion that only exists inside shard_map (lax.axis_index)
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    @property
    def mesh(self):
        return self._mesh


_HCG: Optional[HybridCommunicateGroup] = None
_STRATEGY: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None):
    """fleet.init — build the global Mesh from strategy.hybrid_configs."""
    global _HCG, _STRATEGY
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    degrees = {
        "data": int(hc.get("dp_degree", 1)),
        "pipe": int(hc.get("pp_degree", 1)),
        "sharding": int(hc.get("sharding_degree", 1)),
        "sep": int(hc.get("sep_degree", 1)),
        "model": int(hc.get("mp_degree", 1)),
    }
    n_need = int(np.prod(list(degrees.values())))
    n_have = jax.device_count()
    if n_need == 1:
        degrees["data"] = n_have  # pure DP over all devices by default
    mesh = mesh_lib.make_mesh(
        data=degrees["data"], pipe=degrees["pipe"], sharding=degrees["sharding"],
        sep=degrees["sep"], model=degrees["model"])
    mesh_lib.set_global_mesh(mesh)
    topo = CommunicateTopology(dims=[degrees[n] for n in
                                     ["data", "pipe", "sharding", "sep", "model"]])
    _HCG = HybridCommunicateGroup(topo, mesh)
    _STRATEGY = strategy
    return _HCG


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG


def worker_num():
    return jax.process_count()


def worker_index():
    return jax.process_index()


def distributed_model(model):
    """Reference fleet/model.py:30 — wraps by parallel mode.  GSPMD needs no
    wrapper: sharding annotations do the work.  Returned unchanged."""
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Reference hybrid_parallel_optimizer.py:251.  Functional optimizers are
    already hybrid-safe (grad psum + ZeRO come from shardings)."""
    return optimizer


class Role:
    """Reference fleet/base/role_maker.py Role constants."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Env-driven role maker (reference role_maker.py PaddleCloudRoleMaker):
    reads the PADDLE_* env contract written by distributed.launch."""

    def __init__(self, is_collective=False, **kwargs):
        import os
        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._role = Role.WORKER \
            if os.environ.get("TRAINING_ROLE", "TRAINER") != "PSERVER" \
            else Role.SERVER

    def _is_worker(self):
        return self._role == Role.WORKER

    is_worker = _is_worker

    def _is_server(self):
        return self._role == Role.SERVER

    is_server = _is_server

    def _worker_index(self):
        return self._rank

    worker_index = _worker_index

    def _worker_num(self):
        return self._size

    worker_num = _worker_num

    def _role_id(self):
        return self._rank

    def _get_trainer_endpoints(self):
        import os
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit-args role maker (reference UserDefinedRoleMaker)."""

    def __init__(self, is_collective=False, current_id=0, role=None,
                 worker_num=1, server_endpoints=None, **kwargs):
        super().__init__(is_collective=is_collective)
        self._rank = int(current_id)
        self._size = int(worker_num)
        self._role = role if role is not None else Role.WORKER
        self._server_endpoints = list(server_endpoints or [])


class UtilBase:
    """Reference fleet/utils UtilBase: small cross-worker helpers; the
    in-process build executes them locally."""

    def all_reduce(self, input, mode="sum"):
        import numpy as np
        return np.asarray(input)

    def barrier(self, comm_world="worker"):
        import jax
        jax.effects_barrier()

    def get_file_shard(self, files):
        import os
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        return [f for i, f in enumerate(files) if i % size == rank]

    def print_on_rank(self, message, rank_id=0):
        import os
        if int(os.environ.get("PADDLE_TRAINER_ID", "0")) == int(rank_id):
            print(message)


class _SlotGen:
    """Base for the slot data generators (reference fleet/data_generator):
    subclass and implement generate_sample(line) -> iterator of
    (slot_name, values) lists; run_from_memory/files drive it."""

    def __init__(self):
        self._batch = 1

    def set_batch(self, batch_size):
        self._batch = int(batch_size)

    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(line) returning an iterator")

    def run_from_memory(self, lines):
        out = []
        for line in lines:
            for rec in self.generate_sample(line)():
                out.append(rec)
        return out

    def run_from_files(self, files):
        lines = []
        for path in files:
            with open(path, errors="ignore") as f:
                lines += [ln.rstrip("\n") for ln in f]
        return self.run_from_memory(lines)


class MultiSlotDataGenerator(_SlotGen):
    """Values are numeric lists (reference MultiSlotDataGenerator)."""


class MultiSlotStringDataGenerator(_SlotGen):
    """Values are string lists (reference MultiSlotStringDataGenerator)."""


class Fleet:
    """The reference `fleet.Fleet` facade class.  The module-level
    functions in this package (init/worker_num/...) are the working API —
    this class binds them for users who instantiate `Fleet()` directly."""

    def __init__(self):
        self._role_maker = None

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        return init(role_maker=role_maker, is_collective=is_collective,
                    strategy=strategy)

    def is_first_worker(self):
        return worker_index() == 0

    def worker_index(self):
        return worker_index()

    def worker_num(self):
        return worker_num()

    def is_worker(self):
        return self._role_maker is None or self._role_maker._is_worker()

    def is_server(self):
        return self._role_maker is not None and self._role_maker._is_server()

    @property
    def util(self):
        return UtilBase()


__all__ += ["Fleet", "Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
            "UtilBase", "MultiSlotDataGenerator",
            "MultiSlotStringDataGenerator"]
