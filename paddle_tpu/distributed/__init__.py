"""paddle.distributed parity — GSPMD mesh-native (stage 1: env + collectives API).

Reference: python/paddle/distributed/ (120k LoC; SURVEY.md C20–C33).  The
TPU-native mapping (SURVEY.md §5 'Distributed communication backend'):
ProcessGroup → mesh axis, TCPStore → jax.distributed coordination service,
EagerReducer → gradient psum under jit, p2p send/recv → ppermute over ICI.
"""

from __future__ import annotations

import os

import jax

from . import mesh  # noqa: F401
from .mesh import make_mesh, set_global_mesh, get_global_mesh  # noqa: F401
from . import collective  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, all_reduce, all_gather, reduce_scatter,
    alltoall, broadcast, scatter, reduce, barrier, send, recv,
)
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, reshard,
    dtensor_from_fn, shard_layer,
)
from . import fleet  # noqa: F401
from . import mp_layers  # noqa: F401
from . import parallelize  # noqa: F401
from .parallelize import ShardedTrainState  # noqa: F401
from . import context_parallel  # noqa: F401
from .context_parallel import (  # noqa: F401
    ring_attention, ulysses_attention, context_parallel_attention,
)
from . import pipeline  # noqa: F401
from .pipeline import pipeline_apply, pipeline_1f1b  # noqa: F401
from . import checkpoint  # noqa: F401
from . import launch  # noqa: F401
from . import message_bus  # noqa: F401
from . import rpc  # noqa: F401
from . import fleet_executor  # noqa: F401
from .fleet_executor import FleetExecutor, TaskNode  # noqa: F401
from . import ps  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import moe  # noqa: F401
from .moe import (  # noqa: F401
    MoEConfig, MoELayer, NaiveGate, SwitchGate, GShardGate,
    moe_ffn, top_k_gating, global_scatter, global_gather,
)

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "is_initialized",
           "ParallelEnv", "ReduceOp", "Group", "new_group", "all_reduce",
           "all_gather", "reduce_scatter", "alltoall", "broadcast", "scatter",
           "reduce", "barrier", "send", "recv", "ProcessMesh", "Shard",
           "Replicate", "Partial", "shard_tensor", "reshard", "fleet",
           "dtensor_from_fn", "shard_layer", "make_mesh", "ShardedTrainState",
           "ring_attention", "ulysses_attention", "context_parallel_attention",
           "pipeline_apply", "MoEConfig", "MoELayer", "NaiveGate", "SwitchGate",
           "GShardGate", "moe_ffn", "top_k_gating", "global_scatter",
           "global_gather", "rpc", "launch", "fleet_executor",
           "FleetExecutor", "TaskNode"]

_initialized = False


def init_parallel_env():
    """jax.distributed.initialize when launched multi-process; no-op single."""
    global _initialized
    if _initialized:
        return
    if os.environ.get("PADDLE_TRAINERS_NUM") or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("PADDLE_MASTER")
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("JAX_NUM_PROCESSES", "1")))
        pid = int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("JAX_PROCESS_ID", "0")))
        if coord and nprocs > 1:
            jax.distributed.initialize(coordinator_address=coord, num_processes=nprocs, process_id=pid)
    _initialized = True


def is_initialized():
    return _initialized


def get_rank(group=None):
    try:
        return jax.process_index()
    except Exception:  # noqa: BLE001
        return 0


def get_world_size(group=None):
    # world = all devices (chips), matching the reference's rank-per-device model
    try:
        return jax.device_count()
    except Exception:  # noqa: BLE001
        return 1


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()


# long-tail surface (object collectives, PS entries, fleet datasets, gloo
# shims) — see compat.py
from . import compat as _compat  # noqa: E402
from .compat import *  # noqa: E402,F401,F403

__all__ += list(_compat.__all__)
