"""Sharded training-step builder — fleet.distributed_model/optimizer, TPU-native.

Reference parity: fleet.distributed_model (fleet/model.py:30) +
HybridParallelOptimizer (hybrid_parallel_optimizer.py:251) wrap a model for a
chosen 4D layout.  Here the layout is a Mesh + logical rules, and the "wrap" is
jit in/out shardings: GSPMD inserts every collective (gradient psum over
data, TP allreduces over model, SP allgather/reduce-scatter over sep, ZeRO
all-gathers over sharding) from the annotations — no reducer, no hooks.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib
from ..optimizer.functional import AdamW



def _leaf_name(path):
    """Innermost dict key on a tree path (None for positional leaves)."""
    for p in reversed(path):
        k = getattr(p, "key", None)
        if k is not None:
            return k
    return None


class ShardedTrainState:
    """Bundle of (params, opt_state) shardings + jitted step/init functions."""

    def __init__(self, config, model, mesh: Mesh, optimizer: Optional[AdamW] = None,
                 zero_stage: int = 1, rules=None, donate: bool = True,
                 seq_leaves=None, auto_donate_fix: Optional[bool] = None):
        import dataclasses

        # auto_donate_fix: opt-in Graph Doctor rewrite hook — when the
        # step is built WITHOUT donation (donate=False or a future config
        # that forgets it), lint the jitted step for DONATION_MISSING and
        # re-wrap with the exact donate_argnums fixes.py computes.  None
        # defers to the FLAGS_auto_graph_rewrite framework flag.
        self._auto_donate_fix = auto_donate_fix

        # seq_leaves: optional iterable of batch-dict keys whose dim 1 IS a
        # sequence (sharded over the sep axis); None = rank heuristic (see
        # _leaf_sharding)
        self._seq_leaves = frozenset(seq_leaves) if seq_leaves is not None else None

        if zero_stage not in (0, 1, 2, 3):
            raise ValueError(
                f"zero_stage must be 0..3, got {zero_stage} "
                "(0: replicated; 1: shard optimizer state; 2: + shard grads; "
                "3: + shard params, gather-on-use)")
        self.zero_stage = zero_stage

        mesh_lib.set_global_mesh(mesh)
        # a live sep axis means context parallelism: default to ring attention
        # (the layer that consumes the reference's reserved-but-unused sep axis)
        if (dataclasses.is_dataclass(config)
                and getattr(config, "context_parallel", "n/a") is None
                and "sep" in mesh.axis_names and mesh.shape["sep"] > 1):
            config = dataclasses.replace(config, context_parallel="ring")
        # thread the mesh explicitly so a later ShardedTrainState (which
        # resets the global mesh) cannot alter this state's attention/pipeline
        if (dataclasses.is_dataclass(config)
                and getattr(config, "mesh", "n/a") is None):
            config = dataclasses.replace(config, mesh=mesh)
        self.config = config
        self.model = model          # module with init_params/loss_fn/param_logical_axes
        self.mesh = mesh
        self.optimizer = optimizer or AdamW(learning_rate=1e-4, grad_clip_norm=1.0)
        self.rules = rules or mesh_lib.LOGICAL_RULES

        axes_tree = model.param_logical_axes(config)
        self.param_shardings = mesh_lib.tree_shardings(axes_tree, mesh, self.rules)
        pshape = jax.eval_shape(lambda: model.init_params(config, jax.random.PRNGKey(0)))
        self._pshape = pshape

        zshard = functools.partial(
            mesh_lib.zero_tree_shardings, mesh=mesh, axis="sharding")
        if zero_stage >= 3:
            # stage 3 (FSDP / GroupShardedStage3, group_sharded_stage3.py:59):
            # the STORED params are sharded over the zero axis too; XLA
            # all-gathers each weight at its use site and reduce-scatters its
            # gradient — prefetch/overlap is the XLA scheduler's job.
            self.param_shardings = zshard(self.param_shardings, pshape)

        # optimizer state shardings: m/v/master follow params, then ZeRO-shard
        opt_shape = jax.eval_shape(self.optimizer.init, pshape)
        if zero_stage >= 1:
            m_sh = zshard(jax.tree.map(lambda s: s, self.param_shardings), pshape)
            self.opt_shardings = type(opt_shape)(
                step=NamedSharding(mesh, P()),
                m=m_sh, v=m_sh, master=m_sh)
        else:
            self.opt_shardings = type(opt_shape)(
                step=NamedSharding(mesh, P()),
                m=self.param_shardings, v=self.param_shardings,
                master=self.param_shardings)

        # stage 2 (GroupShardedOptimizerStage2, group_sharded_optimizer_stage2
        # .py:53): constrain grads to the zero-sharded layout so GSPMD lowers
        # the data-parallel all-reduce to reduce-scatter and the optimizer
        # update runs on 1/N of every gradient.
        self._grad_shardings = (
            zshard(jax.tree.map(lambda s: s, self.param_shardings), pshape)
            if zero_stage >= 2 else None)

        # rank-aware batch shardings — see _leaf_sharding: rank-2/3 leaves
        # treat dim 1 as the sequence (ids, masks, per-token labels) and
        # shard (batch, seq); other ranks shard the batch dim only
        self.batch_sharding = NamedSharding(
            mesh, mesh_lib.logical_to_spec(("batch", "seq"), mesh, self.rules))
        self._batch_sharding_1d = NamedSharding(
            mesh, mesh_lib.logical_to_spec(("batch",), mesh, self.rules))

        loss_fn = model.loss_fn
        opt = self.optimizer

        def init_fn(key):
            params = model.init_params(config, key)
            return params, opt.init(params)

        self.init = jax.jit(
            init_fn,
            out_shardings=(self.param_shardings, self.opt_shardings))

        grad_sh = self._grad_shardings
        # models may provide a custom grad path (e.g. llama's hand-scheduled
        # 1F1B pipeline); it falls back to value_and_grad internally
        loss_and_grads = getattr(model, "loss_and_grads", None)

        def step_fn(params, opt_state, batch):
            if loss_and_grads is not None:
                loss, grads = loss_and_grads(params, batch, config)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(params, batch, config)
            if grad_sh is not None:
                grads = jax.lax.with_sharding_constraint(grads, grad_sh)
            params, opt_state = opt.update(grads, opt_state, params)
            return params, opt_state, {"loss": loss,
                                       "grad_norm": _gnorm(grads)}

        # the batch position's in_shardings are built per batch STRUCTURE at
        # first call (generic over whatever leaves the model's loss_fn
        # takes: input_ids/labels/attention_mask/...), rank-aware per leaf
        self._step_fn, self._eval_fn = step_fn, None
        self._donate = donate
        self._step_cache = {}
        self._eval_cache = {}

        def eval_fn(params, batch):
            return loss_fn(params, batch, config)

        self._eval_fn = eval_fn

    def _leaf_sharding(self, x, name=None):
        import numpy as np
        # heuristic: rank-2/3 leaves treat dim 1 as the sequence ((B,S) ids
        # and masks, (B,S,V) soft labels / per-token weights) and shard
        # (batch, seq); rank-1 per-example scalars and rank-4+ leaves
        # ((B,H,W,C) pixels, whose dim 1 is NOT a sequence) shard batch only.
        # The heuristic misfires on rank-2/3 leaves whose dim 1 is NOT a
        # sequence ((B, num_classes) soft targets, (B, 2) spans) — pass
        # seq_leaves={names...} to the constructor to name the sequence
        # leaves explicitly and shard everything else batch-only.
        if self._seq_leaves is not None:
            return (self.batch_sharding if name in self._seq_leaves
                    else self._batch_sharding_1d)
        return (self.batch_sharding if np.ndim(x) in (2, 3)
                else self._batch_sharding_1d)

    def _batch_shardings(self, batch):
        return jax.tree_util.tree_map_with_path(
            lambda path, x: self._leaf_sharding(x, _leaf_name(path)), batch)

    @staticmethod
    def _batch_key(batch):
        # structure AND per-leaf rank (rank decides the leaf's sharding)
        return (jax.tree_util.tree_structure(batch),
                tuple(jnp.ndim(x) for x in jax.tree_util.tree_leaves(batch)))

    def jitted_step(self, batch):
        """The jitted train step specialized to this batch's pytree
        structure, built lazily and cached — step() calls it; the Graph
        Doctor (`paddle_tpu.analysis`, tools/graphlint.py) lints it
        directly so diagnostics cover the exact artifact that runs.
        With `auto_donate_fix` (or FLAGS_auto_graph_rewrite) on, a step
        built without donation is linted and re-wrapped with the exact
        `donate_argnums` the fix suggests — the rewrite tier's donation
        pass applied at the call site."""
        key = self._batch_key(batch)
        jitted = self._step_cache.get(key)
        if jitted is None:
            jitted = self._build_step(batch,
                                      (0, 1) if self._donate else ())
            if not self._donate and self._autofix_enabled():
                jitted = self._autodonate(jitted, batch) or jitted
            self._step_cache[key] = jitted
        return jitted

    def _build_step(self, batch, donate_argnums):
        return jax.jit(
            self._step_fn,
            in_shardings=(self.param_shardings, self.opt_shardings,
                          self._batch_shardings(batch)),
            out_shardings=(self.param_shardings, self.opt_shardings,
                           None),
            donate_argnums=tuple(donate_argnums))

    def _autofix_enabled(self) -> bool:
        if self._auto_donate_fix is not None:
            return bool(self._auto_donate_fix)
        from .. import framework
        return bool(framework.get_state().flags.get(
            "FLAGS_auto_graph_rewrite", False))

    def _autodonate(self, jitted, batch):
        """Lint the freshly-built step abstractly (nothing executes) and,
        when DONATION_MISSING names argnums, rebuild with them donated.
        Any failure keeps the original step — this hook may only help."""
        from .. import analysis
        try:
            pshape, oshape = jax.eval_shape(self.init,
                                            jax.random.PRNGKey(0))
            bshape = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.asarray(x).dtype), batch)
            rep = analysis.analyze(jitted, pshape, oshape, bshape,
                                   checkers=["donation"])
            argnums = sorted({
                f.data.get("argnum")
                for f in rep.by_code("DONATION_MISSING")
                if f.data.get("argnum") is not None})
            if not argnums:
                return None
            return self._build_step(batch, argnums)
        except Exception:  # noqa: BLE001 — advisory hook, never fatal
            return None

    def spmd_in_specs(self, batch) -> list:
        """Flat per-invar PartitionSpec entry lists of the jitted step's
        (params, opt_state, batch) signature — the seed the Graph
        Doctor's SPMD tier (analysis/spmd.py) propagates from.  Exposed
        so the analyzer prices THIS state's layout, not a guess."""
        def entries(s):
            spec = getattr(s, "spec", s)
            return list(spec) if spec is not None else None

        leaves = (jax.tree_util.tree_leaves(self.param_shardings)
                  + jax.tree_util.tree_leaves(self.opt_shardings)
                  + jax.tree_util.tree_leaves(self._batch_shardings(batch)))
        return [entries(s) for s in leaves]

    def spmd_report(self, batch, **kw):
        """Run the Graph Doctor (including the mesh-aware SPMD tier)
        over this state's jitted step, seeded with the state's own
        param/opt/batch shardings.  Nothing executes — the step is
        traced abstractly.  Returns an analysis.Report whose
        COLLECTIVE_BOUND finding carries the comm-vs-compute roofline
        and SPMD_SUMMARY the per-eqn predicted shardings."""
        from .. import analysis

        jitted = self.jitted_step(batch)
        pshape, oshape = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        bshape = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                           jnp.asarray(x).dtype), batch)
        options = dict(kw.pop("options", None) or {})
        options.setdefault("spmd_in_specs", self.spmd_in_specs(batch))
        return analysis.analyze(jitted, pshape, oshape, bshape,
                                mesh=self.mesh, options=options, **kw)

    def step(self, params, opt_state, batch):
        """Jitted train step; specializes (and caches) per batch pytree
        structure so any batch dict the model's loss_fn accepts works."""
        return self.jitted_step(batch)(params, opt_state, batch)

    def eval_step(self, params, batch):
        key = self._batch_key(batch)
        jitted = self._eval_cache.get(key)
        if jitted is None:
            jitted = self._eval_cache[key] = jax.jit(
                self._eval_fn,
                in_shardings=(self.param_shardings,
                              self._batch_shardings(batch)))
        return jitted(params, batch)

    def shard_batch(self, batch):
        # _leaf_sharding reads only np.ndim — no transfer; one device_put.
        # Leaves may be np/jax arrays, python lists, or paddle Tensors
        # (device_put rejects Tensor directly — unwrap the raw array).
        def put(path, x):
            raw = getattr(x, "_data", x)
            if not hasattr(raw, "ndim"):
                raw = jnp.asarray(raw)
            return jax.device_put(raw,
                                  self._leaf_sharding(raw, _leaf_name(path)))

        return jax.tree_util.tree_map_with_path(put, batch)

    # -- distributed checkpoint (reshard-on-load) ---------------------------

    def save(self, path: str, params, opt_state, step: Optional[int] = None,
             extra: Optional[dict] = None) -> None:
        """Shard-by-shard save of (params, opt_state) — see
        distributed.checkpoint; loadable under ANY mesh/zero-stage."""
        from . import checkpoint as ckpt

        meta = dict(extra or {})
        if step is not None:
            meta["step"] = int(step)
        ckpt.save_state(path, {"params": params, "opt": opt_state}, extra=meta)

    def restore(self, path: str):
        """Load a checkpoint RESHARDED onto this state's mesh/zero layout."""
        from . import checkpoint as ckpt

        opt_shape = jax.eval_shape(self.optimizer.init, self._pshape)
        tmpl = {"params": self._pshape, "opt": opt_shape}
        shardings = {"params": self.param_shardings, "opt": self.opt_shardings}
        out = ckpt.load_state(path, tmpl, shardings)
        return out["params"], out["opt"]


def _gnorm(tree):
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in jax.tree.leaves(tree)))
