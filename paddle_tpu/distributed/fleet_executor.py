"""Fleet executor: actor-style interceptor runtime (C34).

Reference parity: `paddle/fluid/distributed/fleet_executor/` —
`FleetExecutor` (fleet_executor.h:36), `Carrier` (carrier.h:50),
`Interceptor` (interceptor.h:51), `ComputeInterceptor`
(compute_interceptor.cc), source/sink/amplifier interceptors, and the
brpc `MessageBus` (message_bus.cc) with `InterceptorMessage`
(interceptor_message.proto: DATA_IS_READY / DATA_IS_USELESS / START / STOP).

TPU-native mapping: a `TaskNode` runs an arbitrary Python callable (in
practice a cached `jax.jit` program — the analog of the reference's
attached ProgramDesc section), carriers host one thread per interceptor
(the reference's TaskLoop threads), and inter-carrier messages ride the
framed TCP `MessageBus` (`native/messagebus.cpp`).  Flow control is the
reference's credit scheme: an upstream edge carries a `buff_size` credit;
DATA_IS_READY spends one, DATA_IS_USELESS refunds one, so at most
`buff_size` microbatches are ever in flight per edge — the property that
bounds pipeline memory.  Unlike the reference (which moves tensors out of
band through scopes), DATA_IS_READY frames carry the payload itself, so a
multi-rank pipeline moves real data.
"""

from __future__ import annotations

import dataclasses
import pickle
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .message_bus import MessageBus

__all__ = ["TaskNode", "Carrier", "FleetExecutor", "InterceptorMessage"]

# message types (interceptor_message.proto)
DATA_IS_READY = "DATA_IS_READY"
DATA_IS_USELESS = "DATA_IS_USELESS"
START = "START"
STOP = "STOP"
DONE = "DONE"  # sink -> executor completion signal
ERR = "ERR"    # interceptor failure, broadcast to every carrier


@dataclasses.dataclass
class InterceptorMessage:
    src_id: int
    dst_id: int
    message_type: str
    scope_idx: int = 0
    payload: Any = None


@dataclasses.dataclass
class TaskNode:
    """One stage of the runtime graph (reference task_node.h).

    `run_fn(scope_idx, inputs)` consumes a dict {upstream_task_id: payload}
    and returns the payload passed downstream.  `max_run_times` is the
    microbatch count; `kind` selects the interceptor ("source" nodes emit
    `feed(scope_idx)`, "sink" nodes collect results, "amplifier" nodes run
    once every `run_per_steps` scopes — the gradient-merge pattern).
    """

    task_id: int
    rank: int = 0
    max_run_times: int = 1
    kind: str = "compute"            # source | compute | sink | amplifier
    run_fn: Optional[Callable[..., Any]] = None
    feed: Optional[Callable[[int], Any]] = None
    run_per_steps: int = 1           # amplifier: fire every k-th scope
    run_at_offset: int = 0
    upstream: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    downstream: List[Tuple[int, int]] = dataclasses.field(default_factory=list)

    def add_upstream_task(self, task_id: int, buff_size: int = 2):
        self.upstream.append((task_id, buff_size))

    def add_downstream_task(self, task_id: int, buff_size: int = 2):
        self.downstream.append((task_id, buff_size))


class _Interceptor(threading.Thread):
    """One actor: a queue, a thread, and the credit bookkeeping."""

    def __init__(self, carrier: "Carrier", node: TaskNode):
        super().__init__(daemon=True, name=f"interceptor-{node.task_id}")
        self.carrier = carrier
        self.node = node
        self.inbox: "queue.Queue[InterceptorMessage]" = queue.Queue()
        # upstream_id -> ready payload queue (credits the upstream spends)
        self.in_ready: Dict[int, queue.Queue] = {
            up: queue.Queue() for up, _ in node.upstream}
        # downstream_id -> remaining buffer credit
        self.out_credit: Dict[int, int] = {
            down: buff for down, buff in node.downstream}
        self.step = 0
        self._stopped = False
        self.error: Optional[BaseException] = None

    # -- messaging ----------------------------------------------------------

    def send(self, dst_id: int, mtype: str, scope_idx: int = 0, payload=None):
        self.carrier.route(InterceptorMessage(
            src_id=self.node.task_id, dst_id=dst_id, message_type=mtype,
            scope_idx=scope_idx, payload=payload))

    def run(self):
        try:
            while not self._stopped:
                msg = self.inbox.get()
                if msg.message_type == STOP:
                    return
                self.handle(msg)
                self.maybe_run()
        except BaseException as e:  # noqa: BLE001 — surface via carrier
            self.error = e
            self.carrier.on_error(self.node.task_id, e)

    def handle(self, msg: InterceptorMessage):
        if msg.message_type == DATA_IS_READY:
            self.in_ready[msg.src_id].put((msg.scope_idx, msg.payload))
        elif msg.message_type == DATA_IS_USELESS:
            self.out_credit[msg.src_id] += 1

    # -- scheduling ---------------------------------------------------------

    def _inputs_ready(self) -> bool:
        return all(not q.empty() for q in self.in_ready.values())

    def _outputs_writable(self) -> bool:
        return all(c > 0 for c in self.out_credit.values())

    def maybe_run(self):
        while (self.step < self.node.max_run_times
               and self._inputs_ready() and self._outputs_writable()):
            scope_idx = self.step
            inputs = {}
            for up, q in self.in_ready.items():
                in_scope, payload = q.get()
                inputs[up] = payload
                self.send(up, DATA_IS_USELESS, scope_idx=in_scope)
            out = self.compute(scope_idx, inputs)
            for down in self.out_credit:
                self.out_credit[down] -= 1
                self.send(down, DATA_IS_READY, scope_idx=scope_idx,
                          payload=out)
            self.step += 1
            if self.step >= self.node.max_run_times:
                self.on_finished()

    def compute(self, scope_idx: int, inputs: Dict[int, Any]):
        if self.node.run_fn is None:
            # pass-through: single upstream payload forwards unchanged
            return next(iter(inputs.values())) if inputs else None
        return self.node.run_fn(scope_idx, inputs)

    def on_finished(self):
        pass

    def stop(self):
        self._stopped = True
        self.inbox.put(InterceptorMessage(-1, self.node.task_id, STOP))


class _SourceInterceptor(_Interceptor):
    """Emits max_run_times microbatches downstream, bounded by credit
    (reference source_interceptor.cc)."""

    def handle(self, msg: InterceptorMessage):
        if msg.message_type == DATA_IS_USELESS:
            self.out_credit[msg.src_id] += 1
        # START just triggers maybe_run

    def _inputs_ready(self) -> bool:
        return True

    def compute(self, scope_idx: int, inputs):
        return self.node.feed(scope_idx) if self.node.feed else scope_idx


class _SinkInterceptor(_Interceptor):
    """Collects results; signals the carrier when all scopes arrived
    (reference sink_interceptor.cc)."""

    def __init__(self, carrier, node):
        super().__init__(carrier, node)
        self.results: List[Any] = []

    def _outputs_writable(self) -> bool:
        return True

    def compute(self, scope_idx: int, inputs: Dict[int, Any]):
        out = (self.node.run_fn(scope_idx, inputs)
               if self.node.run_fn else
               next(iter(inputs.values())) if inputs else None)
        self.results.append(out)
        return out

    def on_finished(self):
        self.carrier.on_sink_done(self.node.task_id, self.results)


class _AmplifierInterceptor(_Interceptor):
    """Runs the fn only every `run_per_steps` scopes at `run_at_offset`
    (reference amplifier_interceptor.cc — gradient-merge / lr-stage nodes);
    other scopes pass data through untouched."""

    def compute(self, scope_idx: int, inputs: Dict[int, Any]):
        if (scope_idx % self.node.run_per_steps) == self.node.run_at_offset \
                and self.node.run_fn is not None:
            return self.node.run_fn(scope_idx, inputs)
        return next(iter(inputs.values())) if inputs else None


_KINDS = {
    "source": _SourceInterceptor,
    "compute": _Interceptor,
    "sink": _SinkInterceptor,
    "amplifier": _AmplifierInterceptor,
}


class Carrier:
    """Hosts this rank's interceptors; routes local messages directly and
    remote ones over the message bus (reference carrier.h:50)."""

    def __init__(self, rank: int, task_rank: Dict[int, int],
                 bus: Optional[MessageBus] = None):
        self.rank = rank
        self.task_rank = dict(task_rank)
        self.bus = bus
        self.interceptors: Dict[int, _Interceptor] = {}
        self._done = threading.Event()
        self._sink_results: Dict[int, List[Any]] = {}
        self._sinks_pending = 0
        self._sinks_total = 0
        self._mu = threading.Lock()
        self.error: Optional[BaseException] = None
        self._bus_thread: Optional[threading.Thread] = None

    def add_interceptor(self, node: TaskNode) -> _Interceptor:
        ic = _KINDS[node.kind](self, node)
        self.interceptors[node.task_id] = ic
        if node.kind == "sink":
            self._sinks_pending += 1
            self._sinks_total += 1
        return ic

    # -- routing ------------------------------------------------------------

    def route(self, msg: InterceptorMessage):
        dst_rank = self.task_rank[msg.dst_id]
        if dst_rank == self.rank:
            self.interceptors[msg.dst_id].inbox.put(msg)
        else:
            assert self.bus is not None, (
                f"task {msg.dst_id} lives on rank {dst_rank} but this "
                f"carrier has no message bus")
            self.bus.send(dst_rank, pickle.dumps(msg))

    def _bus_loop(self):
        while not self._done.is_set():
            got = self.bus.recv(timeout=0.2)
            if got is None:
                continue
            _, payload = got
            try:
                msg: InterceptorMessage = pickle.loads(payload)
            except Exception as e:  # noqa: BLE001 — e.g. an ERR whose
                # exception class dumps fine but fails to unpickle here; a
                # dead bus thread would reinstate the silent-timeout failure
                if self.error is None:
                    self.error = RuntimeError(
                        f"carrier {self.rank}: undecodable inter-carrier "
                        f"frame ({e!r})")
                self._done.set()
                continue
            if msg.message_type == DONE:
                # a remote rank's sinks finished; merge its results.  Only a
                # carrier with NO sinks of its own finishes on this signal —
                # a sink-hosting carrier finishes when ITS sinks drain.
                with self._mu:
                    self._sink_results.update(msg.payload or {})
                    no_own_sinks = self._sinks_total == 0
                if no_own_sinks:
                    self._done.set()
            elif msg.message_type == ERR:
                # remote interceptor failed: surface ITS error here instead
                # of timing out with no diagnosis
                if self.error is None:
                    self.error = msg.payload
                self._done.set()
            else:
                ic = self.interceptors.get(msg.dst_id)
                if ic is not None:
                    ic.inbox.put(msg)

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        for ic in self.interceptors.values():
            ic.start()
        if self.bus is not None:
            self._bus_thread = threading.Thread(
                target=self._bus_loop, daemon=True,
                name=f"carrier-bus-{self.rank}")
            self._bus_thread.start()

    def kick_sources(self):
        for tid, ic in self.interceptors.items():
            if isinstance(ic, _SourceInterceptor):
                ic.inbox.put(InterceptorMessage(-1, tid, START))

    def on_sink_done(self, task_id: int, results: List[Any]):
        with self._mu:
            self._sink_results[task_id] = results
            self._sinks_pending -= 1
            finished = self._sinks_pending <= 0
        if finished:
            # release carriers that host no sink (their wait() blocks on
            # this DONE, mirroring the reference's barrier-on-completion);
            # carry ALL local sink results so remote waiters see them
            with self._mu:
                payload = dict(self._sink_results)
            # broadcast before releasing the local wait(): on this success
            # path every peer connection is already established (no stall
            # risk), and a caller tearing the bus down right after wait()
            # returns must not cut the DONE off
            self._broadcast(InterceptorMessage(task_id, -1, DONE,
                                               payload=payload))
            self._done.set()

    def _broadcast(self, msg: InterceptorMessage):
        """Best-effort send to every other carrier's rank."""
        if self.bus is None:
            return
        frame = pickle.dumps(msg)
        for r in {rk for rk in self.task_rank.values() if rk != self.rank}:
            try:
                self.bus.send(r, frame)
            except (ConnectionError, KeyError):
                pass

    def on_error(self, task_id: int, err: BaseException):
        self.error = err
        # unblock the local wait() FIRST: broadcasting can stall for a full
        # connect-retry window per unreachable peer
        self._done.set()
        try:
            pickle.dumps(err)
            payload = err
        except Exception:  # noqa: BLE001 — unpicklable error
            payload = RuntimeError(f"task {task_id} failed: {err!r}")
        self._broadcast(InterceptorMessage(task_id, -1, ERR, payload=payload))

    def wait(self, timeout: float = 300.0) -> Dict[int, List[Any]]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"carrier {self.rank}: pipeline did not finish in {timeout}s")
        if self.error is not None:
            raise self.error
        return dict(self._sink_results)

    def stop(self):
        self._done.set()
        for ic in self.interceptors.values():
            ic.stop()
        for ic in self.interceptors.values():
            ic.join(timeout=5)
        if self._bus_thread is not None:
            self._bus_thread.join(timeout=5)


class FleetExecutor:
    """Single-rank entry point (reference fleet_executor.h:36): build the
    runtime graph from task nodes, host this rank's carrier, run, collect.

    Multi-rank usage: every rank constructs the same node graph (routing
    needs only task_id->rank), passes its own `rank` and a `MessageBus`
    whose peers map rank->endpoint; sink results land on the sink's rank.
    """

    def __init__(self, nodes: List[TaskNode], rank: int = 0,
                 bus: Optional[MessageBus] = None):
        self.nodes = {n.task_id: n for n in nodes}
        if len(self.nodes) != len(nodes):
            raise ValueError("duplicate task_id in node list")
        self._check_graph()
        task_rank = {n.task_id: n.rank for n in nodes}
        self.carrier = Carrier(rank, task_rank, bus=bus)
        for n in nodes:
            if n.rank == rank:
                self.carrier.add_interceptor(n)

    def _check_graph(self):
        if not any(n.kind == "sink" for n in self.nodes.values()):
            raise ValueError("runtime graph needs at least one sink task "
                             "(completion is signalled by sinks)")
        for n in self.nodes.values():
            for down, buff in n.downstream:
                up_edge = [b for u, b in self.nodes[down].upstream
                           if u == n.task_id]
                if not up_edge:
                    raise ValueError(
                        f"edge {n.task_id}->{down} missing the matching "
                        f"add_upstream_task on {down}")
                if buff <= 0:
                    raise ValueError(f"edge {n.task_id}->{down}: buff_size "
                                     f"must be positive, got {buff}")
            for up, _ in n.upstream:
                if all(d != n.task_id for d, _ in self.nodes[up].downstream):
                    raise ValueError(
                        f"edge {up}->{n.task_id} missing the matching "
                        f"add_downstream_task on {up} (nothing would ever "
                        f"feed task {n.task_id})")

    def run(self, timeout: float = 300.0) -> Dict[int, List[Any]]:
        """Run to completion; returns {sink_task_id: [results per scope]}."""
        self.carrier.start()
        try:
            self.carrier.kick_sources()
            return self.carrier.wait(timeout)
        finally:
            self.carrier.stop()
