"""Device mesh + logical-axis sharding rules — the GSPMD backbone.

Reference parity: fleet's 5-axis hybrid topology
(fleet/base/topology.py:60 CommunicateTopology, axes
["data","pipe","sharding","sep","model"]) and the semi-auto ProcessMesh
(phi/core/distributed/auto_parallel/process_mesh.h:31).  There are no process
groups here: a mesh axis IS the group, and collectives are inserted by XLA
(GSPMD) from sharding annotations — SURVEY.md §5 "ProcessGroup -> Mesh axis".

Axis semantics (same names as the reference topology):
  data     — data parallel (gradient psum)
  sharding — ZeRO: optimizer-state/grad/param sharding; also folds into batch
  sep      — sequence/context parallel (ring attention, Ulysses)
  model    — tensor parallel (Megatron row/col)
  pipe     — pipeline parallel (shard_map + ppermute schedule)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("data", "pipe", "sharding", "sep", "model")

# Logical param/activation axis -> mesh axis (GSPMD rules table).  The analog
# of the reference's per-op SPMD rules (static/operators/dist_matmul.py etc.)
# collapsed into one table, because XLA propagates shardings through ops.
LOGICAL_RULES: Dict[str, Optional[str]] = {
    "vocab": "model",       # VocabParallelEmbedding / column-parallel lm_head
    "heads": "model",       # column-parallel qkv, row-parallel out-proj
    "mlp": "model",         # column-parallel gate/up, row-parallel down
    "embed": None,          # replicated across model axis (fsdp may override)
    "layer": "pipe",        # stacked-layer axis; each pipeline stage owns L/P layers
    "batch": ("data", "sharding"),  # global batch over dp x zero axes
    "seq": "sep",           # sequence parallel
    "expert": "expert",     # expert parallel (MoE meshes add this axis)
    None: None,
}

_GLOBAL_MESH: Optional[Mesh] = None


def make_mesh(data: int = 1, pipe: int = 1, sharding: int = 1, sep: int = 1,
              model: int = 1, devices: Optional[Sequence[Any]] = None,
              extra_axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Create a named device mesh.  Axis order puts `model` innermost so TP
    collectives ride the fastest ICI links (scaling-book layout rule)."""
    sizes = {"data": data, "pipe": pipe, "sharding": sharding, "sep": sep,
             "model": model}
    if extra_axes:
        sizes.update(extra_axes)
    axes = [a for a, n in sizes.items() if n > 1] or ["data"]
    shape = [sizes.get(a, 1) for a in axes]
    if devices is None:
        devices = jax.devices()
    need = int(np.prod(shape))
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    dev = np.asarray(devices[:need]).reshape(shape)
    return Mesh(dev, tuple(axes))


def set_global_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH


def _rule_for(logical: Optional[str], mesh: Mesh, rules=None):
    rules = rules or LOGICAL_RULES
    mesh_axis = rules.get(logical, None)
    if mesh_axis is None:
        return None
    if isinstance(mesh_axis, tuple):
        present = tuple(a for a in mesh_axis if a in mesh.axis_names)
        return present if present else None
    return mesh_axis if mesh_axis in mesh.axis_names else None


def logical_to_spec(axes: Sequence[Optional[str]], mesh: Mesh, rules=None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec for `mesh`."""
    return P(*[_rule_for(a, mesh, rules) for a in axes])


def tree_shardings(axes_tree, mesh: Mesh, rules=None):
    """Map a pytree of logical-axes tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, mesh, rules)),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def zero_shard_spec(spec: P, shape, mesh: Mesh, axis: str = "sharding") -> P:
    """ZeRO sharding: add `axis` to the first unsharded, divisible dimension.

    Applied to optimizer state (stage 1), grads (stage 2) or params (stage 3) —
    the reference's DygraphShardingOptimizer / GroupShardedStage2/3
    (SURVEY.md C28) expressed as a sharding annotation.
    """
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return spec
    n = mesh.shape[axis]
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (p, d) in enumerate(zip(parts, shape)):
        if p is None and d % n == 0:
            parts[i] = axis
            return P(*parts)
        if p is not None:
            used = p if isinstance(p, tuple) else (p,)
            if axis in used:
                return spec  # already sharded on this axis
    return spec


def zero_tree_shardings(param_specs, params_shape_tree, mesh: Mesh,
                        axis: str = "sharding"):
    """Apply zero_shard_spec across a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda s, shp: NamedSharding(
            mesh, zero_shard_spec(s.spec if isinstance(s, NamedSharding) else s,
                                  shp.shape, mesh, axis)),
        param_specs, params_shape_tree,
        is_leaf=lambda x: isinstance(x, (P, NamedSharding)),
    )
