"""Message bus: framed peer-to-peer byte transport.

The one transport under the fleet executor (C34), `distributed.rpc` (C36) and
the parameter server (C35) — the role brpc plays in the reference
(`fluid/distributed/fleet_executor/message_bus.cc`,
`fluid/distributed/rpc/rpc_agent.cc`).  The hot implementation is native C++
(`native/messagebus.cpp`, loaded via ctypes); a pure-Python socket fallback
keeps every feature working when no toolchain is available.

A `MessageBus(my_id)` listens on a TCP port; peers are registered with
`add_peer(peer_id, "host:port")`; `send(peer, bytes)` delivers one frame;
`recv(timeout)` pops `(src_id, bytes)` from the receive queue.  Frames are
opaque — layers above pickle whatever they need.
"""

from __future__ import annotations

import ctypes
import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from .. import native

__all__ = ["MessageBus"]

_HDR = struct.Struct("<qq")  # (src_id, payload_len) little-endian int64 pair


def _split_endpoint(endpoint: str) -> Tuple[str, int]:
    host, _, port = endpoint.rpartition(":")
    return host or "127.0.0.1", int(port or 0)


class _NativeBus:
    def __init__(self, lib, host: str, port: int):
        self._lib = lib
        lib.mb_create.restype = ctypes.c_void_p
        lib.mb_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.mb_port.argtypes = [ctypes.c_void_p]
        lib.mb_add_peer.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                    ctypes.c_char_p, ctypes.c_int]
        lib.mb_send.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                ctypes.c_longlong, ctypes.c_char_p,
                                ctypes.c_longlong]
        lib.mb_recv.restype = ctypes.c_longlong
        lib.mb_recv.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_longlong),
                                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int]
        lib.mb_free.argtypes = [ctypes.c_void_p]
        lib.mb_stop.argtypes = [ctypes.c_void_p]
        lib.mb_destroy.argtypes = [ctypes.c_void_p]
        self._h = lib.mb_create(host.encode(), port)
        if not self._h:
            raise OSError(f"messagebus: cannot bind {host}:{port}")
        self.port = lib.mb_port(self._h)
        # in-flight call guard: stop() may only mb_destroy once no thread
        # can still be inside the library on this handle
        self._calls = 0
        self._cv = threading.Condition()

    def _enter(self):
        with self._cv:
            if self._h is None:
                return None
            self._calls += 1
            return self._h

    def _exit(self):
        with self._cv:
            self._calls -= 1
            if self._calls == 0:
                self._cv.notify_all()

    def add_peer(self, peer_id: int, host: str, port: int):
        h = self._enter()
        if h is None:
            raise ConnectionError("message bus is stopped")
        try:
            self._lib.mb_add_peer(h, peer_id, host.encode(), port)
        finally:
            self._exit()

    def send(self, my_id: int, peer_id: int, payload: bytes) -> int:
        h = self._enter()
        if h is None:
            return -2  # stopped: report like a send failure, never pass NULL
        try:
            return self._lib.mb_send(h, my_id, peer_id, payload, len(payload))
        finally:
            self._exit()

    def recv(self, timeout_ms: int):
        h = self._enter()
        if h is None:
            return -2, None, None
        try:
            src = ctypes.c_longlong()
            buf = ctypes.c_void_p()
            n = self._lib.mb_recv(h, ctypes.byref(src),
                                  ctypes.byref(buf), timeout_ms)
            if n < 0:
                return int(n), None, None
            data = ctypes.string_at(buf, n)
            self._lib.mb_free(buf)
            return int(n), int(src.value), data
        finally:
            self._exit()

    def stop(self):
        with self._cv:
            h, self._h = self._h, None  # new calls refused from here on
        if h is None:
            return
        # wakes blocked recvs (-2) and aborts in-flight connect retries; the
        # bus stays allocated so threads already inside the lib are safe
        self._lib.mb_stop(h)
        with self._cv:
            while self._calls:
                self._cv.wait()
            self._lib.mb_destroy(h)


class _PyBus:
    """Pure-Python fallback with the same framing (interops with native)."""

    def __init__(self, host: str, port: int):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(128)
        self.port = self._srv.getsockname()[1]
        self._queue: "queue.Queue" = queue.Queue()
        self._peers: Dict[int, Tuple[str, int]] = {}
        self._conns: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        self._send_mu = threading.Lock()
        self._stop = threading.Event()
        self._readers = []
        self._acceptor = threading.Thread(target=self._accept_loop, daemon=True)
        self._acceptor.start()
        self.connect_timeout = 30.0

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._reader, args=(conn,), daemon=True)
            t.start()
            self._readers.append(t)

    def _reader(self, conn: socket.socket):
        try:
            while True:
                hdr = self._read_exact(conn, _HDR.size)
                if hdr is None:
                    return
                src, n = _HDR.unpack(hdr)
                payload = self._read_exact(conn, n) if n else b""
                if payload is None:
                    return
                self._queue.put((src, payload))
        finally:
            conn.close()

    @staticmethod
    def _read_exact(conn, n: int) -> Optional[bytes]:
        chunks = []
        while n > 0:
            try:
                b = conn.recv(n)
            except OSError:
                return None
            if not b:
                return None
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def _peer_lock(self, peer_id: int) -> threading.Lock:
        # per-peer send locks (mirrors the native Peer::send_mu): a slow
        # connect to one dead peer must not stall sends to healthy peers
        with self._send_mu:
            lock = self._peer_locks.get(peer_id)
            if lock is None:
                lock = self._peer_locks[peer_id] = threading.Lock()
            return lock

    def add_peer(self, peer_id: int, host: str, port: int):
        with self._peer_lock(peer_id):
            with self._send_mu:
                moved = self._peers.get(peer_id) != (host, port)
                conn = self._conns.pop(peer_id, None) if moved else None
                self._peers[peer_id] = (host, port)
            if conn is not None:
                conn.close()

    def send(self, my_id: int, peer_id: int, payload: bytes) -> int:
        with self._peer_lock(peer_id):
            with self._send_mu:
                addr = self._peers.get(peer_id)
                conn = self._conns.get(peer_id)
            if addr is None:
                return -1
            for _attempt in range(2):
                if conn is None:
                    conn = self._connect(addr)
                    if conn is None:
                        return -2
                    with self._send_mu:
                        self._conns[peer_id] = conn
                try:
                    conn.sendall(_HDR.pack(my_id, len(payload)) + payload)
                    return 0
                except OSError:
                    with self._send_mu:
                        self._conns.pop(peer_id, None)
                    conn.close()
                    conn = None
            return -2

    def _connect(self, addr) -> Optional[socket.socket]:
        deadline = time.time() + self.connect_timeout
        while True:
            try:
                conn = socket.create_connection(addr, timeout=30)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return conn
            except OSError:
                if time.time() >= deadline:
                    return None
                time.sleep(0.1)

    def recv(self, timeout_ms: int):
        try:
            src, data = self._queue.get(timeout=timeout_ms / 1000.0)
            return len(data), src, data
        except queue.Empty:
            return (-2, None, None) if self._stop.is_set() else (-1, None, None)

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._send_mu:
            for conn in self._conns.values():
                conn.close()
            self._conns.clear()


class MessageBus:
    """Named-peer frame transport (native C++ with Python fallback)."""

    def __init__(self, my_id: int, host: str = "127.0.0.1", port: int = 0,
                 backend: str = "auto"):
        self.my_id = int(my_id)
        self.host = host
        lib = native.load("messagebus") if backend in ("auto", "native") else None
        if backend == "native" and lib is None:
            raise RuntimeError("native messagebus unavailable (no toolchain)")
        if lib is not None:
            self._impl = _NativeBus(lib, host, port)
            self.backend = "native"
        else:
            self._impl = _PyBus(host, port)
            self.backend = "python"
        self._stopped = False

    @property
    def port(self) -> int:
        return self._impl.port

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def add_peer(self, peer_id: int, endpoint: str):
        host, port = _split_endpoint(endpoint)
        self._impl.add_peer(int(peer_id), host, port)

    def send(self, peer_id: int, payload: bytes):
        rc = self._impl.send(self.my_id, int(peer_id), payload)
        if rc == -1:
            raise KeyError(f"messagebus: unknown peer {peer_id}")
        if rc != 0:
            raise ConnectionError(
                f"messagebus: send to peer {peer_id} failed (rc={rc})")

    def recv(self, timeout: float = 10.0) -> Optional[Tuple[int, bytes]]:
        """(src_id, payload) or None on timeout; None after stop() too."""
        n, src, data = self._impl.recv(int(timeout * 1000))
        if n < 0:
            return None
        return src, data

    def stop(self):
        if not self._stopped:
            self._stopped = True
            self._impl.stop()
