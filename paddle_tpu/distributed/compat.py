"""Long-tail distributed surface (reference python/paddle/distributed/
__init__.py remainders): object collectives, p2p handles, PS table entry
configs, fleet datasets, gloo shims.

Semantics note: this runtime's eager collectives model "ranks" as shards
of one process over a mesh axis (collective.py).  The object collectives
below follow the same model — with a 1-rank world they are identity;
multi-host object exchange goes through the KV store started by
distributed.launch when one is configured (PADDLE_MASTER env).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional

import jax
import numpy as np

from ..tensor import Tensor, to_tensor

__all__ = [
    "ParallelMode", "DistAttr", "CountFilterEntry", "ProbabilityEntry",
    "ShowClickEntry", "InMemoryDataset", "QueueDataset",
    "all_gather_object", "broadcast_object_list", "scatter_object_list",
    "alltoall_single", "gather", "split", "isend", "irecv", "wait",
    "get_backend", "get_group", "is_available", "destroy_process_group",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release", "io",
]


class ParallelMode:
    """Reference distributed/parallel.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class DistAttr:
    """Reference DistAttr(mesh, sharding_specs) — carried by shard_tensor;
    here a plain record the auto_parallel layer reads."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs or [])

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"sharding_specs={self.sharding_specs})")


class _TableEntry:
    """PS sparse-table admission/eviction config base (reference
    distributed/entry_attr.py)."""

    def _to_attr(self):
        raise NotImplementedError


class CountFilterEntry(_TableEntry):
    def __init__(self, count_filter):
        if not isinstance(count_filter, int) or count_filter < 0:
            raise ValueError("count_filter must be a non-negative integer")
        self._count_filter = count_filter

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ProbabilityEntry(_TableEntry):
    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        self._probability = probability

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


class ShowClickEntry(_TableEntry):
    def __init__(self, show_name, click_name):
        if not isinstance(show_name, str) or not isinstance(click_name, str):
            raise ValueError("show/click names must be strings")
        self._show, self._click = show_name, click_name

    def _to_attr(self):
        return f"show_click_entry:{self._show}:{self._click}"


class InMemoryDataset:
    """Fleet in-memory dataset (reference distributed/fleet/dataset/
    dataset.py InMemoryDataset): files of whitespace-separated numeric
    slots, loaded to memory, shuffled, batched.  `init(use_var=...,
    batch_size=..., parse_fn=...)` — parse_fn overrides the default
    line -> list-of-float parser."""

    def __init__(self):
        self._files: List[str] = []
        self._data: List[Any] = []
        self._batch = 1
        self._parse = None

    def init(self, batch_size=1, use_var=None, pipe_command=None,
             parse_fn=None, **kwargs):
        self._batch = int(batch_size)
        self._parse = parse_fn

    update_settings = init

    def set_filelist(self, files):
        self._files = list(files)

    def load_into_memory(self):
        self._data = []
        for path in self._files:
            with open(path, errors="ignore") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if self._parse is not None:
                        self._data.append(self._parse(line))
                    else:
                        self._data.append(
                            np.asarray([float(v) for v in line.split()],
                                       np.float32))

    def local_shuffle(self):
        from .. import framework
        # fresh permutation each call (epoch), seeded off the global stream
        key = framework.next_rng_key()
        rng = np.random.default_rng(np.asarray(key, np.uint32))
        rng.shuffle(self._data)

    global_shuffle = local_shuffle

    def get_memory_data_size(self):
        return len(self._data)

    def release_memory(self):
        self._data = []

    def __iter__(self):
        for i in range(0, len(self._data), self._batch):
            yield self._data[i:i + self._batch]


class QueueDataset(InMemoryDataset):
    """Streaming variant (reference QueueDataset): iterates files directly
    without the load_into_memory staging."""

    def load_into_memory(self):
        raise RuntimeError(
            "QueueDataset streams from file; iterate it directly "
            "(load_into_memory is the InMemoryDataset API)")

    def __iter__(self):
        buf = []
        for path in self._files:
            with open(path, errors="ignore") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    item = self._parse(line) if self._parse is not None \
                        else np.asarray([float(v) for v in line.split()],
                                        np.float32)
                    buf.append(item)
                    if len(buf) == self._batch:
                        yield buf
                        buf = []
        if buf:
            yield buf


# ---------------------------------------------------------------------------
# object collectives + p2p handles
# ---------------------------------------------------------------------------


def _world():
    try:
        return jax.process_count()
    except Exception:  # noqa: BLE001
        return 1


def all_gather_object(object_list, obj, group=None):
    """Gather picklable objects from every process (reference
    communication/all_gather.py all_gather_object)."""
    if _world() == 1:
        object_list.append(pickle.loads(pickle.dumps(obj)))
        return object_list
    raise NotImplementedError(
        "multi-host object collectives ride the launch KV store; use "
        "distributed.launch + rpc for cross-process python objects")


def broadcast_object_list(object_list, src=0, group=None):
    if _world() == 1:
        return object_list
    raise NotImplementedError(
        "multi-host object collectives ride the launch KV store; use "
        "distributed.launch + rpc for cross-process python objects")


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    if _world() == 1:
        out_object_list.append(
            in_object_list[0] if in_object_list else None)
        return out_object_list
    raise NotImplementedError(
        "multi-host object collectives ride the launch KV store; use "
        "distributed.launch + rpc for cross-process python objects")


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all (reference communication/all_to_all.py):
    rank-blocks of the leading dim are exchanged — with the in-process
    shard model this is the alltoall of collective.py over row blocks."""
    from .collective import _resolve, alltoall
    g = _resolve(group)                # None -> the world group, like every
    n = g.nranks                       # other collective in this build
    x = in_tensor
    if n <= 1:
        return x
    if x.shape[0] % n:
        raise ValueError(
            f"alltoall_single: leading dim {x.shape[0]} must be divisible "
            f"by group size {n}")
    rows = x.shape[0] // n
    parts = [x[i * rows:(i + 1) * rows] for i in range(n)]
    outs = alltoall(parts, group=g)
    from ..ops import concat
    return concat(outs, axis=0)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather shards to dst (reference communication/gather.py); in the
    shard model every rank sees the full gather, dst selects semantics."""
    from .collective import all_gather
    lst = [] if gather_list is None else gather_list
    all_gather(lst, tensor, group=group)
    return lst


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference distributed.split builds a model-parallel linear/embedding
    by chopping the weight across ranks.  Under GSPMD that is a sharding
    annotation, not a runtime split — use the first-class layers instead."""
    raise NotImplementedError(
        "distributed.split: use distributed.mp_layers "
        "(ColumnParallelLinear / RowParallelLinear / "
        "VocabParallelEmbedding) — under GSPMD model parallelism is a "
        "weight sharding annotation, not a runtime weight split")


class _P2PHandle:
    def __init__(self, value=None):
        self._value = value

    def wait(self):
        return self._value

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    from .collective import send
    send(tensor, dst=dst, group=group)     # raises with the TPU guidance
    return _P2PHandle(tensor)              # pragma: no cover


def irecv(tensor, src=0, group=None):
    from .collective import recv
    recv(tensor, src=src, group=group)     # raises with the TPU guidance
    return _P2PHandle(tensor)              # pragma: no cover


def wait(tensor, group=None, use_calc_stream=True):
    """Stream sync (reference communication/wait.py) — forces completion
    of pending async work on the tensor."""
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._data)
    return tensor


def get_backend(group=None):
    """The communication backend name: XLA collectives over the active
    platform (reference returns 'NCCL'/'GLOO')."""
    return f"xla:{jax.default_backend()}"


def get_group(gid=0):
    from . import collective
    if collective._GROUPS:
        for g in collective._GROUPS:
            if g.id == gid:
                return g
        return collective._GROUPS[0]
    return collective.new_group()


def is_available():
    """Reference distributed.is_available: collectives usable?"""
    return True


def destroy_process_group(group=None):
    from . import collective
    if group is None:
        collective._GROUPS.clear()
    elif group in collective._GROUPS:
        collective._GROUPS.remove(group)


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-barrier env (reference gloo shims) — the launch KV store plays
    gloo's role here; single-process is a no-op."""
    return None


def gloo_barrier():
    jax.effects_barrier()


def gloo_release():
    return None


class _IoNamespace:
    """paddle.distributed.io (save/load persistables shims)."""

    @staticmethod
    def save_persistables(executor, dirname, main_program=None,
                          filename=None):
        from ..static import io as _sio
        return _sio.save_persistables(executor, dirname, main_program,
                                      filename)

    @staticmethod
    def load_persistables(executor, dirname, main_program=None,
                          filename=None):
        from ..static import io as _sio
        return _sio.load_persistables(executor, dirname, main_program,
                                      filename)


io = _IoNamespace()
