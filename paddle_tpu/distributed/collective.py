"""Collective communication API — paddle.distributed.* parity, GSPMD-native.

Reference parity: python/paddle/distributed/communication/*.py (all_reduce,
all_gather, reduce_scatter, alltoall, broadcast, scatter, send/recv, stream.*)
over C++ ProcessGroupNCCL (SURVEY.md C20/C21).

TPU-native semantics: there are no process groups — a **Group is a mesh axis**.
In the single-controller JAX model, "rank i's tensor" is shard i of a global
`jax.Array` laid out over that axis.  Each collective here is implemented as a
`shard_map` over the group's mesh axis using XLA collectives (psum, all_gather,
ppermute, all_to_all) compiled onto ICI.  The same functions work unchanged
inside a user's own `shard_map`/jit (pass `axis_name=`), which is the hot path;
the eager wrappers below exist for API/UX parity and for tests.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._shard_map_compat import shard_map

from . import mesh as mesh_lib


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


@dataclasses.dataclass
class Group:
    """A communicator = one mesh axis.  Reference: paddle Group objects from
    distributed/collective.py:176 new_group; here ranks index shards."""
    mesh: Mesh
    axis: str
    id: int = 0

    @property
    def nranks(self) -> int:
        return int(self.mesh.shape[self.axis])

    @property
    def world_size(self) -> int:
        return self.nranks

    def get_group_rank(self, rank):
        return rank

    @property
    def ranks(self) -> List[int]:
        return list(range(self.nranks))


_GROUPS: List[Group] = []


def new_group(ranks: Optional[Sequence[int]] = None, backend: Optional[str] = None,
              mesh: Optional[Mesh] = None, axis: Optional[str] = None) -> Group:
    """Create a group over a mesh axis.  Default: a 1-axis mesh over all (or
    the given) devices — the world group."""
    if mesh is None:
        devices = jax.devices()
        if ranks is not None:
            devices = [devices[r] for r in ranks]
        mesh = Mesh(np.asarray(devices), ("group",))
        axis = "group"
    axis = axis or mesh.axis_names[0]
    g = Group(mesh=mesh, axis=axis, id=len(_GROUPS))
    _GROUPS.append(g)
    return g


def _world_group() -> Group:
    gm = mesh_lib.get_global_mesh()
    if gm is not None:
        return Group(mesh=gm, axis=gm.axis_names[0])
    return new_group()


def _resolve(group: Optional[Group]) -> Group:
    return group if group is not None else _world_group()


def _raw(x):
    data = getattr(x, "_data", x)
    return jnp.asarray(data)


def _rewrap(x, out):
    if hasattr(x, "_data"):
        x.data = out
        return x
    return out


def _sharded_over(arr, g: Group):
    """View the leading dim of `arr` as the per-rank dim, laid out over g.axis."""
    spec = P(g.axis)
    return jax.device_put(arr, NamedSharding(g.mesh, spec))


# ---------------------------------------------------------------------------
# Functional collectives (usable inside user shard_map with axis_name=...)
# ---------------------------------------------------------------------------


def psum(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


def pmax(x, axis_name: str):
    return jax.lax.pmax(x, axis_name)

def pmin(x, axis_name: str):
    return jax.lax.pmin(x, axis_name)


def pmean(x, axis_name: str):
    return jax.lax.pmean(x, axis_name)


def all_gather_in(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter_in(x, axis_name: str, axis: int = 0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all_in(x, axis_name: str, split_axis: int, concat_axis: int):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ppermute(x, axis_name: str, perm):
    return jax.lax.ppermute(x, axis_name, perm=perm)


# ---------------------------------------------------------------------------
# Eager API (paddle.distributed.* signatures)
#
# Convention: the tensor's LEADING dim is the rank dim when the semantics need
# per-rank data (all_gather output, scatter input, alltoall); for all_reduce /
# broadcast the tensor is the same shape on every rank (replicated result).
# ---------------------------------------------------------------------------


_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
    ReduceOp.AVG: jax.lax.pmean,
}


def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True):
    """Sum/replicate over the group.  Rank-sharded leading dim -> reduced full
    value on every shard.  If the group has one rank, identity."""
    g = _resolve(group)
    x = _raw(tensor)
    if g.nranks == 1:
        return _rewrap(tensor, x)
    if op == ReduceOp.PROD:
        def f(s):
            return jnp.exp(jax.lax.psum(jnp.log(s), g.axis))  # pragma: no cover
    else:
        red = _REDUCERS[op]

        def f(s):
            return red(s, g.axis)
    n = g.nranks
    assert x.shape[0] % n == 0, (
        f"all_reduce eager semantics: leading dim {x.shape[0]} is the rank "
        f"dim and must be divisible by group size {n}")
    xs = _sharded_over(x, g)
    # shape-preserving like the reference's in-place all_reduce: every rank
    # block of the leading dim ends up holding the reduction
    out = jax.jit(shard_map(f, mesh=g.mesh, in_specs=P(g.axis),
                            out_specs=P(g.axis)))(xs)
    return _rewrap(tensor, out)


def all_gather(tensor_list, tensor, group: Optional[Group] = None, sync_op=True):
    """Gather each rank-shard into a python list (paddle fills tensor_list)."""
    g = _resolve(group)
    x = _raw(tensor)
    n = g.nranks
    if n == 1:
        tensor_list.append(_rewrap(None, x) if not hasattr(tensor, "_data")
                           else type(tensor)(x))
        return tensor_list
    assert x.shape[0] % n == 0
    xs = _sharded_over(x, g)
    out = jax.jit(shard_map(
        lambda s: jax.lax.all_gather(s, g.axis, axis=0, tiled=True),
        mesh=g.mesh, in_specs=P(g.axis), out_specs=P(), check_vma=False))(xs)
    per = out.shape[0] // n
    for i in range(n):
        piece = out[i * per:(i + 1) * per]
        tensor_list.append(type(tensor)(piece) if hasattr(tensor, "_data") else piece)
    return tensor_list


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op=True):
    g = _resolve(group)
    x = _raw(tensor_or_tensor_list) if not isinstance(tensor_or_tensor_list, (list, tuple)) \
        else jnp.concatenate([_raw(t) for t in tensor_or_tensor_list], axis=0)
    if g.nranks == 1:
        return _rewrap(tensor, x)
    xs = _sharded_over(x, g)
    out = jax.jit(shard_map(
        lambda s: jax.lax.psum_scatter(s, g.axis, scatter_dimension=0, tiled=True),
        mesh=g.mesh, in_specs=P(g.axis), out_specs=P(g.axis)))(xs)
    return _rewrap(tensor, out)


def alltoall(in_tensor_list, out_tensor_list=None, group: Optional[Group] = None,
             sync_op=True):
    g = _resolve(group)
    if isinstance(in_tensor_list, (list, tuple)):
        x = jnp.stack([_raw(t) for t in in_tensor_list], axis=0)
    else:
        x = _raw(in_tensor_list)
    n = g.nranks
    if n == 1:
        out = x
    else:
        xs = _sharded_over(x, g)
        out = jax.jit(shard_map(
            lambda s: jax.lax.all_to_all(s, g.axis, split_axis=0, concat_axis=0,
                                         tiled=True),
            mesh=g.mesh, in_specs=P(g.axis), out_specs=P(g.axis)))(xs)
    if out_tensor_list is not None:
        per = out.shape[0] // n
        for i in range(n):
            out_tensor_list.append(out[i * per:(i + 1) * per])
        return out_tensor_list
    return out


def broadcast(tensor, src: int = 0, group: Optional[Group] = None, sync_op=True):
    """Every shard gets rank-src's value.  Leading dim = rank dim."""
    g = _resolve(group)
    x = _raw(tensor)
    n = g.nranks
    if n == 1:
        return _rewrap(tensor, x)
    assert x.shape[0] % n == 0
    per = x.shape[0] // n
    src_block = jax.lax.dynamic_slice_in_dim(x, src * per, per, axis=0)
    out = jnp.tile(src_block, (n,) + (1,) * (x.ndim - 1))
    return _rewrap(tensor, out)


def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op=True):
    g = _resolve(group)
    if tensor_list is not None:
        stacked = jnp.stack([_raw(t) for t in tensor_list], axis=0)
    else:
        stacked = _raw(tensor)
    n = g.nranks
    per = stacked.shape[0] // n
    # each "rank" keeps its slice; we return the sharded global array
    out = _sharded_over(stacked.reshape((n * per,) + stacked.shape[2:])
                        if tensor_list is not None else stacked, g)
    return _rewrap(tensor, out)


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group: Optional[Group] = None,
           sync_op=True):
    return all_reduce(tensor, op=op, group=group)  # result visible to dst too


def barrier(group: Optional[Group] = None):
    jax.effects_barrier()


def send(tensor, dst: int = 0, group=None, sync_op=True):  # pragma: no cover
    raise NotImplementedError(
        "point-to-point send/recv map to ppermute inside shard_map on TPU; "
        "use distributed.pipeline (ppermute-based) instead")


def recv(tensor, src: int = 0, group=None, sync_op=True):  # pragma: no cover
    raise NotImplementedError(
        "point-to-point send/recv map to ppermute inside shard_map on TPU; "
        "use distributed.pipeline (ppermute-based) instead")
