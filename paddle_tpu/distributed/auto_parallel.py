"""Semi-auto parallel API — paddle.distributed.auto_parallel parity.

Reference: shard_tensor distributed/auto_parallel/api.py:86, DistTensor
phi/core/distributed/auto_parallel/dist_tensor.h:26, TensorDistAttr
dist_attr.h:74, ProcessMesh process_mesh.h:31, ReshardFunction
reshard_function.h:29 ({p,r,s}-to-{p,r,s} reshard rules).

TPU-native: this IS jax.sharding.  ProcessMesh -> Mesh, TensorDistAttr
placements -> PartitionSpec, shard_tensor -> device_put(NamedSharding),
reshard -> device_put with a new sharding (XLA emits the collective), and
SPMD rule inference (matmul.cc spmd_rules) -> GSPMD propagation inside jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "dtensor_from_fn", "reshard", "shard_layer", "get_placements"]


class Placement:
    pass


@dataclasses.dataclass(frozen=True)
class Shard(Placement):
    """Shard along tensor dim `dim` over the corresponding mesh axis."""
    dim: int

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


@dataclasses.dataclass(frozen=True)
class Replicate(Placement):
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return True

    def is_partial(self):
        return False


@dataclasses.dataclass(frozen=True)
class Partial(Placement):
    """Pending-reduction placement (reference: partial status in dist_attr).
    XLA has no user-visible partial state outside jit; resharding a Partial
    applies the reduction immediately."""
    reduce_type: str = "sum"

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return True


class ProcessMesh:
    """Reference process_mesh.h:31 — an N-D array of device ids with axis names."""

    def __init__(self, mesh: Union[Sequence, np.ndarray], dim_names: Optional[List[str]] = None):
        arr = np.asarray(mesh)
        self._shape = list(arr.shape)
        self._ids = arr.reshape(-1).tolist()
        self._dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devices = jax.devices()
        dev_arr = np.asarray([devices[i] for i in self._ids]).reshape(arr.shape)
        self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    @property
    def shape(self):
        return self._shape

    @property
    def process_ids(self):
        return self._ids

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def mesh(self):
        return self._jax_mesh

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"


def _placements_to_spec(placements: Sequence[Placement], ndim: int,
                        pmesh: ProcessMesh) -> P:
    parts: List[Any] = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            axis_name = pmesh.dim_names[mesh_dim]
            cur = parts[pl.dim]
            if cur is None:
                parts[pl.dim] = axis_name
            elif isinstance(cur, tuple):
                parts[pl.dim] = cur + (axis_name,)
            else:
                parts[pl.dim] = (cur, axis_name)
    return P(*parts)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, stop_gradient: bool = True):
    """Place a tensor on the mesh with the given placements -> jax.Array with
    a NamedSharding (the DistTensor analog)."""
    raw = getattr(data, "_data", data)
    raw = jnp.asarray(raw)
    spec = _placements_to_spec(placements, raw.ndim, mesh)
    out = jax.device_put(raw, NamedSharding(mesh.mesh, spec))
    if hasattr(data, "_data"):
        data.data = out
        return data
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements: Sequence[Placement],
                    *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Change placements — XLA inserts the needed collective (the reference's
    ReshardFunction table: p2r=allreduce, s2r=allgather, r2s=slice...)."""
    raw = getattr(dist_tensor, "_data", dist_tensor)
    spec = _placements_to_spec(placements, raw.ndim, mesh)
    out = jax.device_put(raw, NamedSharding(mesh.mesh, spec))
    if hasattr(dist_tensor, "_data"):
        dist_tensor.data = out
        return dist_tensor
    return out


def get_placements(arr) -> List[Placement]:
    """Recover placement objects from a NamedSharding-ed jax.Array."""
    raw = getattr(arr, "_data", arr)
    sh = raw.sharding
    if not isinstance(sh, NamedSharding):
        return [Replicate()]
    out: List[Placement] = []
    for mesh_dim, name in enumerate(sh.mesh.axis_names):
        placed = Replicate()
        for tdim, part in enumerate(sh.spec):
            names = part if isinstance(part, tuple) else (part,)
            if name in [n for n in names if n]:
                placed = Shard(tdim)
        out.append(placed)
    return out


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Apply shard_fn(name, layer, mesh) to each sublayer's params in place."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):  # replicate by default
            for p in sublayer.parameters(include_sublayers=False):
                shard_tensor(p, mesh, [Replicate()] * len(mesh.shape))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    return layer
