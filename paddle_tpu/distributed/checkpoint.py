"""Distributed sharded checkpoint with reshard-on-load.

Reference parity: the reference saves/loads distributed states through
`python/paddle/distributed/auto_parallel/static/converter.py` (reshard a
checkpoint onto a different parallel layout), `dist_saver.py`, and the group
sharded utils (`fleet/meta_parallel/sharding/group_sharded_utils.py`).  The
TPU-native design:

  * **Save** writes each pytree leaf as its device shards (`.npy` files, one
    per unique shard — replicas deduped by `replica_id == 0`) plus a single
    `metadata.json` holding the tree structure, global shapes/dtypes and the
    global index every shard covers.  No host gathering: a 70B state never
    materializes unsharded anywhere.
  * **Load** takes TARGET shardings (any mesh, any zero stage, any device
    count) and builds each array with `jax.make_array_from_callback` — the
    callback assembles exactly the requested global slice from whichever
    saved shards overlap it.  That is reshard-on-load: save on an 8-chip
    dp×zero mesh, resume on 4 chips (or 256) with a different layout.

Format (version 1)::

    ckpt_dir/
      metadata.json       # {"version": 1, "leaves": {key: {shape, dtype,
                          #   shards: [{file, index: [[start, stop], ...]}]}},
                          #  "extra": {...user metadata...}}
      arrays/<key>/<n>.npy
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["save_state", "load_state", "latest_step", "step_dir",
           "CheckpointManager"]

_VERSION = 1


def _key_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return ".".join(parts) or "_root"


def _safe(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key)


def _norm_index(index, shape):
    """Slice tuple -> [[start, stop], ...] over every dim."""
    out = []
    idx = list(index) + [slice(None)] * (len(shape) - len(index))
    for sl, d in zip(idx, shape):
        start, stop, step = sl.indices(d)
        assert step == 1, "strided shards unsupported"
        out.append([int(start), int(stop)])
    return out


def save_state(path: str, tree: Any, extra: Optional[Dict] = None,
               overwrite: bool = True) -> None:
    """Save a pytree of (possibly sharded) jax.Arrays shard-by-shard.

    Multi-host contract: every process writes only its addressable
    `replica_id == 0` shards under process-prefixed filenames plus a
    per-process manifest; after a cross-host barrier, process 0 merges the
    manifests into the final metadata.json (whose presence marks the
    checkpoint complete — `latest_step` keys off it).
    """
    if os.path.exists(os.path.join(path, "metadata.json")) and not overwrite:
        raise FileExistsError(f"checkpoint already exists at {path}")
    os.makedirs(os.path.join(path, "arrays"), exist_ok=True)
    proc = jax.process_index()
    nproc = jax.process_count()
    leaves_meta: Dict[str, Any] = {}
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    for kpath, leaf in flat:
        key = _key_str(kpath)
        arr = jnp.asarray(leaf)
        adir = os.path.join(path, "arrays", _safe(key))
        os.makedirs(adir, exist_ok=True)
        shards_meta = []
        shards = getattr(arr, "addressable_shards", None)
        if not shards:  # plain host value (process 0 writes it)
            if proc == 0:
                np.save(os.path.join(adir, "p0_0.npy"), np.asarray(arr))
                shards_meta.append({"file": "p0_0.npy",
                                    "index": _norm_index((), arr.shape)})
        else:
            for i, sh in enumerate(shards):
                if getattr(sh, "replica_id", 0) != 0:
                    continue  # replicas carry no new bytes
                fname = f"p{proc}_{i}.npy"
                np.save(os.path.join(adir, fname), np.asarray(sh.data))
                shards_meta.append({"file": fname,
                                    "index": _norm_index(sh.index, arr.shape)})
        leaves_meta[key] = {
            "shape": [int(d) for d in arr.shape],
            "dtype": jnp.dtype(arr.dtype).name,
            "shards": shards_meta,
        }
    part = os.path.join(path, f"manifest.{proc}.json")
    with open(part + ".tmp", "w") as f:
        json.dump(leaves_meta, f)
    os.replace(part + ".tmp", part)

    if nproc > 1:  # all shard files + manifests durable before the merge
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_save:{path}")
    if proc == 0:
        merged: Dict[str, Any] = {}
        for p in range(nproc):
            with open(os.path.join(path, f"manifest.{p}.json")) as f:
                for key, lm in json.load(f).items():
                    if key in merged:
                        merged[key]["shards"].extend(lm["shards"])
                    else:
                        merged[key] = lm
        meta = {"version": _VERSION, "leaves": merged, "extra": extra or {}}
        tmp = os.path.join(path, "metadata.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(path, "metadata.json"))


def _read_meta(path: str) -> Dict:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    if meta.get("version") != _VERSION:
        raise ValueError(
            f"checkpoint version {meta.get('version')} != supported {_VERSION}")
    return meta


def load_extra(path: str) -> Dict:
    return _read_meta(path).get("extra", {})


def _assemble(path: str, key: str, lm: Dict, index) -> np.ndarray:
    """Assemble the global slice `index` of leaf `key` from saved shards."""
    import ml_dtypes  # noqa: F401 — registers bf16 & friends with numpy

    shape = lm["shape"]
    want = _norm_index(index, shape)
    out_shape = [b - a for a, b in want]
    out = np.empty(out_shape, dtype=np.dtype(lm["dtype"]))
    filled = 0
    for sh in lm["shards"]:
        have = sh["index"]
        inter = [[max(a0, b0), min(a1, b1)]
                 for (a0, a1), (b0, b1) in zip(have, want)]
        if any(a >= b for a, b in inter):
            continue
        src = np.load(os.path.join(path, "arrays", _safe(key), sh["file"]),
                      mmap_mode="r")
        src_sl = tuple(slice(a - h0, b - h0)
                       for (a, b), (h0, _) in zip(inter, have))
        dst_sl = tuple(slice(a - w0, b - w0)
                       for (a, b), (w0, _) in zip(inter, want))
        out[dst_sl] = src[src_sl]
        filled += int(np.prod([b - a for a, b in inter]))
    if filled < int(np.prod(out_shape)):
        raise ValueError(f"checkpoint shards for '{key}' do not cover the "
                         f"requested slice (got {filled} of {np.prod(out_shape)}"
                         " elements) — corrupt or partial save")
    return out


def load_state(path: str, template: Any, shardings: Any = None) -> Any:
    """Load a checkpoint onto NEW shardings (reshard-on-load).

    template: pytree of arrays or ShapeDtypeStructs giving the tree
    structure + shapes/dtypes to restore (e.g. from `jax.eval_shape` of the
    init function).  shardings: matching pytree of `jax.sharding.Sharding`
    (or None entries → fully replicated on the default device).
    """
    meta = _read_meta(path)
    leaves_meta = meta["leaves"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    if shardings is None:
        flat_sh = [None] * len(flat)
    else:
        flat_sh = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: x is None
            or isinstance(x, jax.sharding.Sharding))
    out = []
    for (kpath, leaf), sh in zip(flat, flat_sh):
        key = _key_str(kpath)
        if key not in leaves_meta:
            raise KeyError(f"checkpoint at {path} has no leaf '{key}' "
                           f"(has: {sorted(leaves_meta)[:8]}...)")
        lm = leaves_meta[key]
        shape, dtype = tuple(lm["shape"]), np.dtype(lm["dtype"])
        want_shape = tuple(getattr(leaf, "shape", shape))
        if want_shape != shape:
            raise ValueError(f"shape mismatch for '{key}': checkpoint "
                             f"{shape} vs template {want_shape}")
        if sh is None:
            arr = jnp.asarray(_assemble(path, key, lm, ()))
            if hasattr(leaf, "dtype"):
                arr = arr.astype(leaf.dtype)
            out.append(arr)
            continue

        def cb(index, key=key, lm=lm):
            return _assemble(path, key, lm, index)

        arr = jax.make_array_from_callback(shape, sh, cb)
        if hasattr(leaf, "dtype") and arr.dtype != leaf.dtype:
            arr = arr.astype(leaf.dtype)
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


# -- step-numbered checkpoint directories (train-loop convenience) ----------


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step:08d}")


def latest_step(root: str) -> Optional[int]:
    """Largest step with a complete (metadata-bearing) checkpoint, or None."""
    if not os.path.isdir(root):
        return None
    best = None
    for name in os.listdir(root):
        m = re.fullmatch(r"step_(\d+)", name)
        if m and os.path.exists(os.path.join(root, name, "metadata.json")):
            s = int(m.group(1))
            best = s if best is None or s > best else best
    return best


class CheckpointManager:
    """Async training-loop checkpointing with retention (reference
    auto-checkpoint, base/incubate/checkpoint/auto_checkpoint.py, and the
    orbax CheckpointManager pattern the TPU ecosystem standardizes on).

    `save(step, tree)` snapshots device arrays to host immediately (one
    blocking device->host copy) and writes the checkpoint on a background
    thread, so the train loop never stalls on disk IO; `keep` bounds how
    many complete checkpoints remain (oldest pruned after each successful
    save).  `wait()` drains pending writes (call before exit);
    `restore(template)` loads the newest complete step.
    """

    def __init__(self, root: str, keep: int = 3, save_interval: int = 1):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.root = root
        self.keep = keep
        self.save_interval = max(1, save_interval)
        self._executor = None
        self._pending = []
        self._errors: list = []

    # -- plumbing -----------------------------------------------------------

    def _pool(self):
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt")
        return self._executor

    def _write(self, step: int, host_tree, extra):
        try:
            save_state(step_dir(self.root, step), host_tree, extra=extra)
            self._prune()
        except BaseException as e:  # noqa: BLE001 — surfaced on next call
            self._errors.append(e)

    def _prune(self):
        steps = sorted(
            int(m.group(1)) for m in (
                re.fullmatch(r"step_(\d+)", n)
                for n in os.listdir(self.root)) if m)
        complete = [s for s in steps if os.path.exists(
            os.path.join(step_dir(self.root, s), "metadata.json"))]
        for s in complete[:-self.keep] if len(complete) > self.keep else []:
            shutil.rmtree(step_dir(self.root, s), ignore_errors=True)

    def _raise_pending_errors(self):
        if self._errors:
            e = self._errors[0]
            self._errors = []
            raise RuntimeError("async checkpoint write failed") from e

    # -- API ----------------------------------------------------------------

    def should_save(self, step: int) -> bool:
        return step % self.save_interval == 0

    def save(self, step: int, tree, extra: Optional[Dict] = None,
             block: bool = False):
        """Snapshot now, write in the background (or inline when block).

        Multi-process runs save SYNCHRONOUSLY through save_state directly:
        its cross-host barrier must run on the main thread (a background
        barrier would interleave with training collectives and deadlock),
        and per-shard addressable writes must not be gathered.  Async mode
        is the single-process path: the device->host copy happens up front
        so the caller may donate/overwrite device buffers immediately
        (the gathered-to-host layout is fine there — load_state reshards
        on load)."""
        self._raise_pending_errors()
        if jax.process_count() > 1 or block:
            if jax.process_count() > 1:
                save_state(step_dir(self.root, step), tree, extra=extra)
                self._prune()
            else:
                self._write(step, jax.tree.map(np.asarray, tree), extra)
            self._raise_pending_errors()
            return None
        host_tree = jax.tree.map(lambda a: np.asarray(a), tree)
        fut = self._pool().submit(self._write, step, host_tree, extra)
        self._pending.append(fut)
        self._pending = [f for f in self._pending if not f.done()]
        return fut

    def wait(self):
        """Drain pending writes; re-raise the first background failure."""
        for f in list(self._pending):
            f.result()
        self._pending = []
        self._raise_pending_errors()

    def latest_step(self) -> Optional[int]:
        return latest_step(self.root)

    def restore(self, template, shardings=None, step: Optional[int] = None):
        """Load `step` (default: newest complete) into template's structure."""
        self.wait()
        s = self.latest_step() if step is None else step
        if s is None:
            raise FileNotFoundError(f"no complete checkpoint under {self.root}")
        return load_state(step_dir(self.root, s), template,
                          shardings=shardings), s
