"""paddle.distributed.rpc — async RPC between named workers (C36).

Reference parity: `python/paddle/distributed/rpc/rpc.py` (init_rpc /
rpc_sync / rpc_async / shutdown / get_worker_info over a brpc RpcAgent,
`fluid/distributed/rpc/rpc_agent.h`).  TPU-native mapping: the transport is
the framed TCP `MessageBus` (native C++, `native/messagebus.cpp`); the
rendezvous master is the launcher's `KVStore` (the role TCPStore plays in the
reference); callables and payloads travel as cloudpickle so lambdas and
closures work cross-process.

Worker model: one RPC worker per process.  `init_rpc` rendezvouses all
workers at the master endpoint, exchanges (name, rank, ip, port), and starts
a dispatcher thread + a small executor pool.  `rpc_sync/rpc_async(to, fn,
args, kwargs)` run `fn` on the destination worker and return the result (or
re-raise the remote exception, traceback text attached).  `shutdown()` is a
barrier through the master, so no worker tears its bus down while a peer
still awaits a response.
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional

try:
    import cloudpickle as _pickle
except ImportError:  # pragma: no cover - cloudpickle is in the image
    import pickle as _pickle  # type: ignore[no-redef]

from ..launch import KVClient, KVStore
from ..message_bus import MessageBus, _split_endpoint

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "WorkerInfo"]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_TIMEOUT = 120.0


class _Agent:
    def __init__(self, name: str, rank: int, world_size: int,
                 master_endpoint: str):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.master_endpoint = master_endpoint
        self.store: Optional[KVStore] = None
        if rank == 0:
            host, port = _split_endpoint(master_endpoint)
            self.store = KVStore(host, port)
            if port == 0:  # ephemeral master: publish via env
                os.environ["PADDLE_MASTER_ENDPOINT"] = self.store.endpoint
                self.master_endpoint = self.store.endpoint
        self.kv = KVClient(self.master_endpoint)

        self.bus = MessageBus(rank)
        self.kv.set(f"rpc/worker/{rank}",
                    f"{name}|{self.bus.host}|{self.bus.port}")
        self.workers: Dict[str, WorkerInfo] = {}
        by_rank: Dict[int, WorkerInfo] = {}
        for r in range(world_size):
            raw = self.kv.wait(f"rpc/worker/{r}", timeout=300)
            if not raw:
                raise TimeoutError(f"rpc rendezvous: worker {r} never joined")
            wname, ip, port_s = raw.split("|")
            if wname in self.workers:
                raise ValueError(f"worker name {wname!r} is not unique")
            info = WorkerInfo(wname, r, ip, int(port_s))
            self.workers[wname] = info
            by_rank[r] = info
            self.bus.add_peer(r, f"{ip}:{port_s}")
        self.by_rank = by_rank

        self._req_id = itertools.count()
        self._pending: Dict[int, Future] = {}
        self._pending_mu = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=int(os.environ.get("PADDLE_RPC_WORKERS", "4")),
            thread_name_prefix=f"rpc-{name}")
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name=f"rpc-recv-{name}")
        self._stop = threading.Event()
        self._dispatcher.start()

    # -- wire ---------------------------------------------------------------

    def _dispatch_loop(self):
        while not self._stop.is_set():
            got = self.bus.recv(timeout=0.2)
            if got is None:
                continue
            src, payload = got
            try:
                msg = _pickle.loads(payload)
            except Exception:  # noqa: BLE001 — corrupt frame: drop
                continue
            kind = msg[0]
            if kind == "req":
                self._pool.submit(self._run_request, src, msg)
            elif kind == "resp":
                _, req_id, ok, value = msg
                with self._pending_mu:
                    fut = self._pending.pop(req_id, None)
                if fut is not None:
                    if ok:
                        fut.set_result(value)
                    else:
                        fut.set_exception(value)

    def _run_request(self, src: int, msg):
        _, req_id, fn, args, kwargs = msg
        try:
            out = ("resp", req_id, True, fn(*args, **(kwargs or {})))
        except BaseException as e:  # noqa: BLE001 — ship it back to caller
            import traceback
            e.remote_traceback = traceback.format_exc()  # type: ignore[attr-defined]
            out = ("resp", req_id, False, e)
        try:
            payload = _pickle.dumps(out)
        except Exception as e:  # noqa: BLE001 — unpicklable result/exception:
            # the caller must still get a response, not a silent timeout
            payload = _pickle.dumps(("resp", req_id, False, RuntimeError(
                f"rpc: response of {getattr(fn, '__name__', fn)!r} is not "
                f"picklable: {e!r}")))
        try:
            self.bus.send(src, payload)
        except (ConnectionError, KeyError):
            pass  # caller went away (shutdown/elastic restart)

    def submit(self, to: str, fn, args, kwargs):
        if to not in self.workers:
            raise ValueError(
                f"unknown rpc worker {to!r}; known: {sorted(self.workers)}")
        req_id = next(self._req_id)
        fut: Future = Future()
        with self._pending_mu:
            self._pending[req_id] = fut
        payload = _pickle.dumps(("req", req_id, fn, tuple(args or ()),
                                 dict(kwargs or {})))
        try:
            self.bus.send(self.workers[to].rank, payload)
        except BaseException:
            with self._pending_mu:
                self._pending.pop(req_id, None)
            raise
        return req_id, fut

    def result_of(self, req_id: int, fut: Future, timeout):
        """Future.result with cleanup: a timed-out/abandoned request must
        not leave its entry in _pending for the agent's lifetime."""
        try:
            return fut.result(timeout=timeout)
        except BaseException:
            with self._pending_mu:
                self._pending.pop(req_id, None)
            raise

    # -- teardown -----------------------------------------------------------

    def barrier(self, key: str, timeout: float = 300.0):
        n = self.kv.incr(f"rpc/barrier/{key}")
        if n == self.world_size:
            self.kv.set(f"rpc/barrier_done/{key}", "1")
        if not self.kv.wait(f"rpc/barrier_done/{key}", timeout=timeout):
            raise TimeoutError(f"rpc barrier {key}: {n}/{self.world_size}")

    def stop(self):
        self._stop.set()
        self._dispatcher.join(timeout=5)
        self._pool.shutdown(wait=True)
        self.bus.stop()
        if self.store is not None:
            self.store.shutdown()


_agent: Optional[_Agent] = None


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Join the RPC gang as worker `name` (reference rpc.py:init_rpc).

    rank/world_size/master default to the launcher's env contract
    (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER).
    """
    global _agent
    if _agent is not None:
        raise RuntimeError("init_rpc called twice (call shutdown() first)")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0")) if rank is None else rank
    world_size = (int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
                  if world_size is None else world_size)
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:0")
    _agent = _Agent(name, rank, world_size, master_endpoint)
    _agent.barrier("init")
    return _agent


def _require_agent() -> _Agent:
    if _agent is None:
        raise RuntimeError("rpc not initialized; call init_rpc() first")
    return _agent


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout: float = _DEFAULT_TIMEOUT):
    """Run `fn(*args, **kwargs)` on worker `to`; returns a Future whose
    `.wait()`/`.result()` yields the value or re-raises the remote error."""
    agent = _require_agent()
    req_id, fut = agent.submit(to, fn, args, kwargs)
    fut.wait = lambda t=timeout: agent.result_of(  # type: ignore[attr-defined]
        req_id, fut, timeout=None if t in (None, -1) else t)
    return fut


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout: float = _DEFAULT_TIMEOUT):
    agent = _require_agent()
    req_id, fut = agent.submit(to, fn, args, kwargs)
    return agent.result_of(req_id, fut,
                           timeout=None if timeout in (None, -1) else timeout)


def shutdown():
    """Barrier, then tear down the agent (reference rpc.py:shutdown)."""
    global _agent
    if _agent is None:
        return
    _agent.barrier("shutdown")
    # _Agent.stop's pool.shutdown(wait=True) drains any responses this
    # worker still owes before the bus goes down
    _agent.stop()
    _agent = None


def get_worker_info(name: str) -> WorkerInfo:
    return _require_agent().workers[name]


def get_all_worker_infos():
    a = _require_agent()
    return [a.by_rank[r] for r in sorted(a.by_rank)]


def get_current_worker_info() -> WorkerInfo:
    a = _require_agent()
    return a.by_rank[a.rank]
