"""Cost model + parallel-config auto-tuner (SURVEY C49 / C32 planner).

Reference analog: `python/paddle/distributed/auto_tuner/tuner.py:19` (search
over dp/mp/pp/sharding candidates), `auto_tuner/prune.py` (memory/validity
pruning) and the static auto-parallel cost model
(`auto_parallel/static/cost_model.py`).  The reference tunes by LAUNCHING
trial runs; a TPU mesh is predictable enough to rank analytically first —
this module builds the roofline estimate (MXU time + ICI collective time +
pipeline bubble + HBM fit) for every legal mesh factorization and returns
the ranked plans.  `measure=` hooks a callable for trial-run refinement of
the top-k, which is the reference's behavior.

The arithmetic follows the public scaling-book recipe: collective cost =
bytes x (axis-1)/axis / ICI bandwidth; pipeline bubble = (p-1)/(m+p-1);
ZeRO-3 adds a param all-gather per step.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, List, Optional

__all__ = ["ChipSpec", "Plan", "CostModel", "AutoTuner",
           "auto_parallelize", "pick_pp_schedule", "measure_step_time",
           "tune_with_trials", "V5E", "V5P"]


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-chip hardware numbers (bf16 peak, HBM, ICI per direction)."""
    name: str
    peak_flops: float          # bf16 FLOP/s
    hbm_bytes: float
    ici_bw: float              # bytes/s per link direction
    mxu_efficiency: float = 0.55   # achievable fraction of peak on big GEMMs


V5E = ChipSpec("v5e", 197e12, 16e9, 4.5e10)
V5P = ChipSpec("v5p", 459e12, 95e9, 9e10)


@dataclasses.dataclass
class Plan:
    data: int
    sharding: int
    model: int
    pipe: int
    sep: int
    zero_stage: int
    micro_batches: int
    step_time: float           # seconds (estimated)
    mem_bytes: float           # per-chip bytes (estimated)
    breakdown: dict

    @property
    def mesh_sizes(self):
        return {"data": self.data, "sharding": self.sharding,
                "model": self.model, "pipe": self.pipe, "sep": self.sep}


class CostModel:
    """Analytic roofline for one transformer train step on a mesh."""

    def __init__(self, chip: ChipSpec):
        self.chip = chip

    # -- model arithmetic ---------------------------------------------------
    @staticmethod
    def _stats(c):
        E, F, V, L = (c.hidden_size, c.intermediate_size, c.vocab_size,
                      c.num_hidden_layers)
        D, Hq, Hkv = c.hd, c.num_attention_heads, c.num_key_value_heads
        layer = E * Hq * D + 2 * E * Hkv * D + Hq * D * E + 3 * E * F
        n_params = L * layer + 2 * E * V + E  # + embeds/head/norms
        return n_params, layer

    def estimate(self, config, n_tokens_global: int, seq: int, sizes: dict,
                 zero_stage: int, micro_batches: int) -> Optional[Plan]:
        """Step-time + memory for one mesh plan; None when it cannot run."""
        c = self.chip
        dp = sizes["data"] * sizes["sharding"]
        tp, pp, sp = sizes["model"], sizes["pipe"], sizes["sep"]
        chips = dp * tp * pp * sp
        N, layer_params = self._stats(config)
        E, L, S = config.hidden_size, config.num_hidden_layers, seq
        if L % pp or config.num_attention_heads % tp or S % sp:
            return None
        if n_tokens_global % (dp * micro_batches * S):
            return None
        B_local = n_tokens_global // (dp * S)           # sequences per dp rank
        mb_seqs = B_local // micro_batches
        if mb_seqs == 0:
            return None

        # ---- memory (bytes/chip): bf16 params + f32 master+m+v (14 B/param
        # replicated; ZeRO divides the f32 trio, stage 3 also the bf16 copy)
        shard = sizes["sharding"] if zero_stage >= 1 else 1
        p_local = N / (tp * pp)
        opt_b = 12 * p_local / shard
        par_b = 2 * p_local / (shard if zero_stage >= 3 else 1)
        grad_b = 2 * p_local / (shard if zero_stage >= 2 else 1)
        # activations: remat keeps ~2 live layer activations per microbatch
        # in flight; pp stages hold up to `pp` microbatches (1F1B bound)
        act_per_layer = 2 * mb_seqs * (S // sp) * E * 4
        act_b = act_per_layer * 2 * max(pp, 1) + 2 * mb_seqs * (S // sp) * config.vocab_size * 4 / max(tp, 1)
        mem = opt_b + par_b + grad_b + act_b
        if mem > c.hbm_bytes * 0.92:
            return None

        # ---- compute time: 6N + attention flops per token
        attn = L * 2 * S * config.num_attention_heads * config.hd
        flops_tok = 6.0 * (N + attn / 3)  # fwd+bwd, causal-averaged
        t_compute = (n_tokens_global * flops_tok) / (
            chips * c.peak_flops * c.mxu_efficiency)

        # ---- collectives (per step, overlapped factor 0.5 vs compute)
        def ring(bytes_, axis):
            return 0.0 if axis <= 1 else 2 * bytes_ * (axis - 1) / axis / c.ici_bw

        # grad reduce over dp (bf16 grads once per step); with ZeRO-2+ each
        # rank only reduces its 1/shard slice of the gradients
        t_dp = ring(2 * p_local / (1 if zero_stage < 2 else shard), dp)
        # tp: 4 allreduces of activations per layer per microbatch chunk
        act_bytes = 2 * mb_seqs * (S // sp) * E
        t_tp = micro_batches * L / pp * 4 * ring(act_bytes, tp)
        # sp ring: kv bytes circulate once per layer
        kv_bytes = 2 * 2 * mb_seqs * (S // sp) * config.num_key_value_heads * config.hd
        t_sp = 0.0 if sp <= 1 else micro_batches * (L / pp) * (sp - 1) * kv_bytes / c.ici_bw
        # zero-3 param all-gather (bf16 params once fwd + once bwd)
        t_z3 = ring(2 * 2 * p_local, shard) if zero_stage >= 3 else 0.0
        t_comm = 0.5 * (t_dp + t_tp + t_sp + t_z3)  # partial overlap

        # ---- pipeline bubble
        bubble = (pp - 1) / (micro_batches + pp - 1) if pp > 1 else 0.0
        t = (t_compute + t_comm) / max(1e-9, 1 - bubble)
        return Plan(sizes["data"], sizes["sharding"], tp, pp, sp, zero_stage,
                    micro_batches, t, mem,
                    {"compute": t_compute, "comm": t_comm, "bubble": bubble,
                     "mem_opt": opt_b, "mem_act": act_b})


def _factorizations(n: int, axes: int):
    """All ordered tuples of `axes` divisors with product n."""
    if axes == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, axes - 1):
                yield (d,) + rest


class AutoTuner:
    """Enumerate legal plans, prune by memory, rank by estimated step time
    (reference auto_tuner/tuner.py:19 search loop + prune.py)."""

    def __init__(self, chip: ChipSpec = V5P,
                 zero_stages=(1, 2, 3), max_tp: int = 8,
                 micro_batch_candidates=(1, 2, 4, 8, 16)):
        self.cost = CostModel(chip)
        self.zero_stages = zero_stages
        self.max_tp = max_tp
        self.mb_cands = micro_batch_candidates

    def tune(self, config, n_chips: int, global_batch: int, seq: int,
             use_sep: bool = False, top_k: int = 5,
             measure: Optional[Callable[[Plan], float]] = None) -> List[Plan]:
        n_tokens = global_batch * seq
        plans: List[Plan] = []
        for (dp, sh, tp, pp, sp) in _factorizations(n_chips, 5):
            if tp > self.max_tp or (sp > 1 and not use_sep):
                continue
            sizes = {"data": dp, "sharding": sh, "model": tp,
                     "pipe": pp, "sep": sp}
            for z in self.zero_stages:
                if z >= 1 and sh == 1 and z != min(self.zero_stages):
                    continue  # zero stages differ only via the sharding axis
                for mb in self.mb_cands:
                    if pp > 1 and mb < pp:
                        continue  # 1F1B needs m >= p
                    p = self.cost.estimate(config, n_tokens, seq, sizes, z, mb)
                    if p is not None:
                        plans.append(p)
        plans.sort(key=lambda p: p.step_time)
        # dedupe identical mesh+schedule keeping the fastest
        seen, uniq = set(), []
        for p in plans:
            key = (p.data, p.sharding, p.model, p.pipe, p.sep, p.zero_stage,
                   p.micro_batches)
            if key not in seen:
                seen.add(key)
                uniq.append(p)
        uniq = uniq[:max(top_k, 1)]
        if measure is not None:  # trial-run refinement, reference-style
            timed, errors = [], []
            for p in uniq:
                try:
                    timed.append((measure(p), p))
                except Exception as e:  # noqa: BLE001 — a failed trial
                    # prunes its candidate (reference behavior), it must
                    # not sink the plans that measured fine
                    errors.append((p, e))
            if not timed:
                raise RuntimeError(
                    "every trial-run candidate failed; first error: "
                    f"{errors[0][1]!r}") from errors[0][1]
            timed.sort(key=lambda tp_: tp_[0])
            for t, p in timed:
                p.step_time = t
            uniq = [p for _, p in timed]
        if not uniq:
            raise RuntimeError(
                f"no parallel plan fits: model does not fit {n_chips} x "
                f"{self.cost.chip.name} ({self.cost.chip.hbm_bytes/1e9:.0f} GB)"
                " — add chips, raise zero_stage options, or shrink the batch")
        return uniq


def _thread_pp_plan(config, best: "Plan", global_batch: int, seq: int,
                    chip: "ChipSpec"):
    """Copy the plan's pipeline decisions into the model config: the ranked
    microbatch count, and — when the user left pp_schedule unset — the
    analytically picked schedule (gpipe unless its O(M) stash would not fit
    beside the plan's params/opt/grads)."""
    import dataclasses as _dc
    if best.pipe <= 1:
        return config
    if getattr(config, "pp_microbatches", "n/a") is None:
        config = _dc.replace(config, pp_microbatches=best.micro_batches)
    if getattr(config, "pp_schedule", "n/a") is None:
        dpz = best.data * best.sharding
        mb_seqs = max(1, global_batch // max(dpz, 1)
                      // max(best.micro_batches, 1))
        reserved = best.mem_bytes - best.breakdown.get("mem_act", 0.0)
        schedule, _ = pick_pp_schedule(config, best.pipe,
                                       best.micro_batches, seq, mb_seqs,
                                       chip, reserved_bytes=reserved,
                                       sp=best.sep)
        config = _dc.replace(config, pp_schedule=schedule)
    return config


def measure_step_time(config, model, plan: "Plan", global_batch: int,
                      seq: int, devices=None, steps: int = 2,
                      optimizer=None, chip: "ChipSpec" = None) -> float:
    """Trial-run one plan: build its mesh + ShardedTrainState on the real
    devices, run `steps` timed steps on a synthetic batch, return
    seconds/step.  This is the reference tuner's launch-and-time loop
    (auto_tuner/tuner.py: each candidate config is RUN, not just scored)
    in-process."""
    import time

    import jax
    import numpy as np

    from . import mesh as mesh_lib
    from .parallelize import ShardedTrainState

    devices = list(devices if devices is not None else jax.devices())
    mesh = mesh_lib.make_mesh(devices=devices, **plan.mesh_sizes)
    # trial-run the SAME schedule the deployment would use
    cfg = _thread_pp_plan(config, plan, global_batch, seq, chip or V5E)
    st = ShardedTrainState(cfg, model, mesh, optimizer,
                           zero_stage=plan.zero_stage)
    params, opt = st.init(jax.random.PRNGKey(0))
    toks = np.random.default_rng(0).integers(
        0, config.vocab_size, (global_batch, seq + 1))
    import jax.numpy as _jnp
    batch = st.shard_batch(model.lm_batch_from_tokens(
        _jnp.asarray(toks, _jnp.int32)))
    params, opt, m = st.step(params, opt, batch)   # compile
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, m = st.step(params, opt, batch)
    float(m["loss"])                                # force completion
    return (time.perf_counter() - t0) / steps


def tune_with_trials(config, model, n_chips: int, global_batch: int,
                     seq: int, chip: "ChipSpec" = V5E, top_k: int = 3,
                     devices=None, steps: int = 2, optimizer=None,
                     use_sep: bool = False, **tuner_kw) -> List["Plan"]:
    """Analytic ranking refined by MEASURED trial runs of the top-k plans
    (the reference AutoTuner's full loop: prune -> launch -> time ->
    pick), via tune()'s measure= hook — each surviving plan's step_time
    becomes the MEASURED seconds/step (also kept in
    breakdown["measured_step_time"])."""

    def _measure(p):
        t = measure_step_time(config, model, p, global_batch, seq,
                              devices=devices, steps=steps,
                              optimizer=optimizer, chip=chip)
        p.breakdown["measured_step_time"] = t
        return t

    tuner = AutoTuner(chip=chip, **tuner_kw)
    return tuner.tune(config, n_chips, global_batch, seq, top_k=top_k,
                      use_sep=use_sep, measure=_measure)


def pick_pp_schedule(config, pp: int, micro_batches: int, seq: int,
                     mb_seqs: int, chip: "ChipSpec" = V5E,
                     reserved_bytes: Optional[float] = None, sp: int = 1):
    """Analytic GPipe-by-AD vs recompute-1F1B default per (S, L, P, M)
    (VERDICT r3 weak #5; the tradeoff distributed/pipeline.py documents).

    Recompute-1F1B re-executes each stage's forward inside backward
    (~4x-fwd total FLOPs vs ~3x for GPipe-by-AD) but stashes only O(P)
    in-flight microbatch activations where GPipe-by-AD stashes O(M); the
    (P-1)/(M+P-1) bubble is identical.  So GPipe is the default and 1F1B
    wins exactly when the O(M) stash would not fit the chip.

    `reserved_bytes`: the plan's non-activation memory (params + optimizer
    + grads) — the stash budget is what remains of HBM after it; without it
    a flat half-HBM budget is assumed.  `sp`: live sep-axis size (the
    sequence is S//sp per shard).  Activations are priced at 4 B/element,
    the SAME accounting CostModel.estimate validated the plan's HBM fit
    with — a cheaper dtype here could approve a gpipe stash the fit check
    never covered.

    Returns (schedule, details) with the stash estimates so callers can
    log the decision."""
    E = config.hidden_size
    act = mb_seqs * (seq // max(sp, 1)) * E * 4.0  # boundary act / microbatch
    resid = 2 * act                             # live remat residuals
    gpipe_stash = micro_batches * act + resid
    f1b_stash = pp * act + resid
    if reserved_bytes is not None:
        budget = max(chip.hbm_bytes * 0.92 - reserved_bytes,
                     chip.hbm_bytes * 0.05)
    else:
        budget = chip.hbm_bytes * 0.5           # rest: params+opt+grads
    schedule = "gpipe" if gpipe_stash <= budget else "1f1b"
    return schedule, {
        "gpipe_stash_bytes": int(gpipe_stash),
        "f1b_stash_bytes": int(f1b_stash),
        "stash_budget_bytes": int(budget),
        "relative_compute": {"gpipe": 3.0, "1f1b": 4.0},
        "bubble": (pp - 1) / (micro_batches + pp - 1) if pp > 1 else 0.0,
    }


def auto_parallelize(config, model, n_chips: Optional[int] = None,
                     global_batch: int = 8, seq: Optional[int] = None,
                     chip: Optional[ChipSpec] = None, use_sep: bool = False,
                     optimizer=None, devices=None, **tuner_kw):
    """Plan -> Mesh -> ShardedTrainState in one call (the C32 planner loop:
    reference Engine.prepare + planner_v2 choose a dist-attr assignment;
    here the AutoTuner ranks mesh factorizations and the winner becomes the
    GSPMD layout).

    Returns (state, plan).  `devices` defaults to jax.devices(); `chip`
    defaults by device kind (v5e/v5p table) falling back to V5E numbers.
    """
    import jax

    from . import mesh as mesh_lib
    from .parallelize import ShardedTrainState

    devices = list(devices if devices is not None else jax.devices())
    n_chips = n_chips or len(devices)
    if len(devices) < n_chips:
        raise ValueError(f"need {n_chips} devices, have {len(devices)}")
    if chip is None:
        kind = getattr(devices[0], "device_kind", "").lower()
        chip = V5P if "v5p" in kind else V5E
    seq = seq or getattr(config, "max_position_embeddings", 2048)

    tuner = AutoTuner(chip=chip, **tuner_kw)
    best = tuner.tune(config, n_chips, global_batch, seq,
                      use_sep=use_sep, top_k=1)[0]
    mesh = mesh_lib.make_mesh(devices=devices[:n_chips], **best.mesh_sizes)
    if best.pipe > 1:
        # thread the plan's SCHEDULE into the config too — the cost model
        # ranked this plan at `micro_batches` microbatches with a 1F1B
        # bubble; running at the default (= pp) would make the winner
        # slower than plans it beat
        config = _thread_pp_plan(config, best, global_batch, seq, chip)
    state = ShardedTrainState(config, model, mesh, optimizer,
                              zero_stage=best.zero_stage)
    return state, best
