"""Tensor-parallel (Megatron) + sequence-parallel layers — GSPMD-native.

Reference parity: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding:44,
ColumnParallelLinear:312, RowParallelLinear:524, ParallelCrossEntropy:729;
fleet/utils/sequence_parallel_utils.py — ScatterOp:83 / AllGatherOp:109 /
ReduceScatterOp:125, ColumnSequenceParallelLinear:228,
RowSequenceParallelLinear:340; RNG tracker fleet/layers/mpu/random.py:34.

TPU-native design: a "parallel layer" is an ordinary layer whose weight is
device_put with a NamedSharding over the `model` mesh axis and whose
activations carry `lax.with_sharding_constraint`s.  The collectives of the
reference (identity-fwd/allreduce-bwd, allgather, reduce_scatter) are
inserted by GSPMD where the annotations demand them — including the
sequence-parallel allgather/reduce-scatter pair around row/col linears.
Works both under jit and eagerly (jax executes sharded eager ops SPMD).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib
from .. import framework
from ..nn.layer import Layer
from ..nn import initializer as I
from ..nn import functional as F
from ..tensor import Tensor

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
           "ParallelCrossEntropy", "ColumnSequenceParallelLinear",
           "RowSequenceParallelLinear", "ScatterOp", "AllGatherOp",
           "ReduceScatterOp", "RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "mark_as_sequence_parallel_parameter"]


def _mesh():
    m = mesh_lib.get_global_mesh()
    if m is None:
        raise RuntimeError("call fleet.init(...) (or set_global_mesh) first")
    return m


def _tp_size():
    m = _mesh()
    return int(m.shape.get("model", 1))


def _shard_param(p: Tensor, spec: P):
    m = _mesh()
    if all(a is None or (isinstance(a, str) and a not in m.axis_names)
           for a in spec):
        return p
    p.data = jax.device_put(p.data, NamedSharding(m, spec))
    return p


def _constrain(x, spec: P):
    m = _mesh()
    names = [a for a in jax.tree.leaves(tuple(spec)) if isinstance(a, str)]
    if any(n not in m.axis_names for n in names):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(m, spec))


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over `model`.  Reference
    mp_layers.py:44 masks out-of-range ids and allreduces; GSPMD derives the
    same program from the weight sharding."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num = num_embeddings
        self._dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _shard_param(self.weight, P("model", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    """y = x @ W[:, shard] — output-dim sharded.  Reference mp_layers.py:312.
    gather_output=True adds an allgather (sharding constraint to replicated)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        assert out_features % _tp_size() == 0, (
            f"out_features {out_features} not divisible by mp degree {_tp_size()}")
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _shard_param(self.weight, P(None, "model"))
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        if self.bias is not None:
            _shard_param(self.bias, P("model"))

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        spec = (None,) * (y.data.ndim - 1)
        if self.gather_output:
            y.data = _constrain(y.data, P(*spec, None))
        else:
            y.data = _constrain(y.data, P(*spec, "model"))
        return y


class RowParallelLinear(Layer):
    """y = x[shard] @ W[shard, :] (+allreduce).  Reference mp_layers.py:524."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        assert in_features % _tp_size() == 0
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _shard_param(self.weight, P("model", None))
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        spec = (None,) * (y.data.ndim - 1)
        y.data = _constrain(y.data, P(*spec, None))  # psum folded by GSPMD
        return y


class ParallelCrossEntropy(Layer):
    """Softmax CE over a vocab-sharded logits tensor.  Reference
    mp_layers.py:729 (c_softmax_with_cross_entropy op)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        logits = input.data if hasattr(input, "_data") else input
        labels = label.data if hasattr(label, "_data") else label
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
        ll = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32),
                                 axis=-1)
        loss = (logz - ll)[..., 0]
        if self.ignore_index is not None:
            valid = labels != self.ignore_index
            loss = jnp.where(valid, loss, 0.0)
        return Tensor(loss[..., None], stop_gradient=False) \
            if hasattr(input, "_data") else loss[..., None]


# ---------------------------------------------------------------------------
# Sequence parallel (TP-SP, reference sequence_parallel_utils.py)
# ---------------------------------------------------------------------------


def ScatterOp(x, axis=0):
    """Split along seq dim over model axis (sequence_parallel_utils.py:83)."""
    raw = getattr(x, "_data", x)
    spec = [None] * raw.ndim
    spec[axis] = "model"
    out = _constrain(raw, P(*spec))
    if hasattr(x, "_data"):
        x.data = out
        return x
    return out


def GatherOp(x, axis=0):
    raw = getattr(x, "_data", x)
    out = _constrain(raw, P(*([None] * raw.ndim)))
    if hasattr(x, "_data"):
        x.data = out
        return x
    return out


AllGatherOp = GatherOp


def ReduceScatterOp(x, axis=0):
    """Partial-sum -> scatter over seq dim (sequence_parallel_utils.py:125).
    Under GSPMD the reduce and the scatter fuse into one reduce_scatter."""
    return ScatterOp(x, axis=axis)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Input arrives seq-sharded; allgather seq before the column matmul
    (reference :228).  The allgather is the constraint transition."""

    def forward(self, x):
        raw = getattr(x, "_data", x)
        full = _constrain(raw, P(*([None] * raw.ndim)))
        if hasattr(x, "_data"):
            x.data = full
        y = F.linear(x, self.weight, self.bias)
        spec = (None,) * (y.data.ndim - 1)
        y.data = _constrain(y.data, P(*spec, "model"))
        return y


class RowSequenceParallelLinear(RowParallelLinear):
    """Output leaves seq-sharded via reduce_scatter (reference :340)."""

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        nd = y.data.ndim
        spec = [None] * nd
        spec[0] = "model"  # seq-major layout: (S, B, E) in the reference
        y.data = _constrain(y.data, P(*spec))
        return y


def mark_as_sequence_parallel_parameter(param):
    """Reference :190 registers allreduce hooks for SP params; with GSPMD the
    gradient reduction is derived from shardings, so this only tags."""
    param.is_sequence_parallel = True
    return param


# ---------------------------------------------------------------------------
# Model-parallel RNG tracker (reference mpu/random.py:34)
# ---------------------------------------------------------------------------


class RNGStatesTracker:
    """Named RNG states so dropout can be replicated (global seed) or distinct
    (local seed) across TP ranks — reference RNGStatesTracker."""

    def __init__(self):
        self._states = {}

    def reset(self):
        self._states.clear()

    def add(self, name, seed):
        if name in self._states:
            raise ValueError(f"seed name {name} already exists")
        self._states[name] = (int(seed), 0)  # framework.Generator state

    def get_states_tracker(self):
        return dict(self._states)

    def set_states_tracker(self, states):
        self._states = dict(states)

    class _Guard:
        def __init__(self, tracker, name):
            self.tracker, self.name = tracker, name

        def __enter__(self):
            gen = framework.default_generator()
            self._saved = gen.get_state()
            gen.set_state(self.tracker._states[self.name])
            return self

        def __exit__(self, *a):
            gen = framework.default_generator()
            self.tracker._states[self.name] = gen.get_state()
            gen.set_state(self._saved)

    def rng_state(self, name="model-parallel-rng"):
        if name not in self._states:
            raise ValueError(f"seed name {name} not added")
        return RNGStatesTracker._Guard(self, name)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed: int = 2024):
    """Reference mpu/random.py:88 — global seed + per-rank local seed."""
    _RNG_STATE_TRACKER.reset()
    local = seed + 2718  # single-controller: one local stream
    _RNG_STATE_TRACKER.add("global_seed", seed)
    _RNG_STATE_TRACKER.add("local_seed", local)
