"""Custom-kernel extension API (SURVEY C-custom-op; VERDICT r3 missing #2).

Reference: users extend PaddlePaddle with external kernels through
`PD_BUILD_OP` (`paddle/phi/api/ext/op_meta_info.h:943`) and build them with
`python/paddle/utils/cpp_extension/cpp_extension.py` (setup/load).  The op
then behaves like a built-in: dispatched through the eager API, AMP lists,
autograd, and usable inside compiled programs.

TPU-native re-design — two tiers, one registration point:

1. `register_custom_op(name, fn, vjp=..., ...)` — the DEVICE path.  `fn` is
   any JAX-traceable callable (jnp composition or a Pallas kernel).  An
   optional user vjp makes it differentiable even when fn itself is not
   (e.g. a fwd-only Pallas kernel).  The op is:
     * dispatched through `tensor.apply_op` (eager tape, AMP cast lists,
       FLAGS_check_nan_inf — identical treatment to built-ins),
     * registered into `ops.registry` (the dtype/grad/sharding test sweep
       picks it up when a `sample` is provided),
     * bound as `paddle_tpu.<name>` and as a `Tensor` method.

2. `load(name, sources=...)` — the HOST path, the literal cpp_extension
   analog.  C++ sources are compiled with the in-image toolchain
   (g++ -shared -fPIC), exported symbols use a plain C ABI
   (`extern "C" void op(const float* in, float* out, const int64_t* shape,
   int64_t ndim)`), and the kernel is bridged into JAX with
   `jax.pure_callback`, so it works eagerly AND inside jit (XLA inserts the
   host transfer; on TPU this is a device->host->device round trip — use
   tier 1/Pallas for hot ops).  A vjp may be supplied (another C++ kernel or
   any python fn) to make it differentiable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["register_custom_op", "get_custom_op", "load", "CustomOp",
           "CppExtension"]

_CUSTOM_OPS = {}
_LOCK = threading.Lock()


class CustomOp:
    """A registered custom op: callable over Tensors, dispatching through
    apply_op (so tape/AMP/flags apply) with the user's fn (+ optional vjp)."""

    def __init__(self, name: str, fn: Callable, vjp: Optional[Callable],
                 nondiff: Sequence[int] = ()):
        self.name = name
        self._raw_fn = fn
        if vjp is not None:
            # user-supplied gradient: custom_vjp with residuals = all inputs.
            # vjp signature: vjp(cotangent, *primal_inputs) -> grads tuple
            # (one per differentiable input, None allowed).
            cfn = jax.custom_vjp(fn)

            def fwd(*args):
                return fn(*args), args

            def bwd(res, ct):
                grads = vjp(ct, *res)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                out = tuple(
                    jnp.zeros_like(a) if g is None else g
                    for g, a in zip(grads, res))
                return out

            cfn.defvjp(fwd, bwd)
            self.fn = cfn
        else:
            self.fn = fn
        self.nondiff = tuple(nondiff)

    def __call__(self, *args, **kwargs):
        from ..tensor import Tensor, apply_op, to_tensor
        targs = [a if isinstance(a, Tensor) or not isinstance(
            a, (np.ndarray, jnp.ndarray, float, int, list)) else to_tensor(a)
            for a in args]
        if kwargs:
            import functools
            f = functools.partial(self.fn, **kwargs)
        else:
            f = self.fn
        return apply_op(self.name, f, *targs, nondiff=self.nondiff)


def register_custom_op(name: str, fn: Optional[Callable] = None, *,
                       vjp: Optional[Callable] = None,
                       sharding: str = "elementwise",
                       dtypes: Tuple[str, ...] = ("float32",),
                       sample: Optional[Callable] = None,
                       tol: Optional[dict] = None,
                       nondiff: Sequence[int] = (),
                       bind_tensor_method: bool = True):
    """Register a custom device op.  Usable as a decorator:

        @register_custom_op("fused_bias_gelu", vjp=my_vjp)
        def fused_bias_gelu(x, b): ...        # jnp or Pallas

    After registration `paddle_tpu.fused_bias_gelu(t)` dispatches through the
    framework op path, differentiates (user vjp or JAX AD), runs under jit,
    and — when `sample` is given — joins the generated registry sweep like
    any built-in (the analog of the reference's custom-op OpTest hook,
    test/custom_op/test_custom_relu_op_setup.py)."""

    def deco(f):
        import paddle_tpu as _pt
        from ..ops import registry
        from ..tensor import Tensor

        op = CustomOp(name, f, vjp, nondiff=nondiff)
        with _LOCK:  # checks AND mutations under one lock, registry first
            if name in _CUSTOM_OPS:
                raise ValueError(f"custom op '{name}' already registered")
            if hasattr(_pt, name):
                raise ValueError(
                    f"custom op '{name}' collides with an existing "
                    f"paddle_tpu attribute")
            registry.register(name, dtypes=dtypes, has_vjp=True,
                              sample=sample, tol=tol, sharding=sharding)
            _CUSTOM_OPS[name] = op
            setattr(_pt, name, op)
            if bind_tensor_method and not hasattr(Tensor, name):
                setattr(Tensor, name, lambda self, *a, **k: op(self, *a, **k))
        return op

    if fn is not None:
        return deco(fn)
    return deco


def get_custom_op(name: str) -> CustomOp:
    return _CUSTOM_OPS[name]


# ---------------------------------------------------------------------------
# Tier 2: C++ host kernels (the literal cpp_extension)
# ---------------------------------------------------------------------------


class CppExtension:
    """Build-spec record (API parity with reference CppExtension; here it
    just carries sources/flags for load())."""

    def __init__(self, sources, extra_compile_args=None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])


def _compile(name: str, sources, extra_cflags, build_directory):
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    lib_path = os.path.join(build_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    stale = (not os.path.exists(lib_path) or any(
        os.path.getmtime(lib_path) < os.path.getmtime(s) for s in srcs))
    if stale:
        cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread"]
               + list(extra_cflags or []) + srcs + ["-o", lib_path + ".tmp"])
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"cpp_extension build failed for '{name}':\n{e.stderr}") from e
        os.replace(lib_path + ".tmp", lib_path)
    return ctypes.CDLL(lib_path)


class _CppKernel:
    """One exported C symbol bridged into JAX via pure_callback.

    C ABI: extern "C" void sym(const T* in..., T* out,
                               const int64_t* shape, int64_t ndim)
    where `shape` is INPUT 0's shape (all inputs must share it — the check
    below guards the pointer contract).  By default the output also has
    input 0's shape/dtype (the elementwise contract, covering the
    reference's custom_relu-class ops); a `shape_fn(*input_shapes) ->
    out_shape` / `dtype_fn(*input_dtypes) -> out_dtype` pair lets a kernel
    produce a differently-shaped/typed output (reductions etc.) — the
    analog of the reference's SetInferShapeFn/SetInferDtypeFn
    (paddle/phi/api/ext/op_meta_info.h).  The output buffer is
    zero-initialized so accumulate-style kernels are safe."""

    def __init__(self, cdll, symbol: str, n_inputs: int, dtype=np.float32,
                 shape_fn: Optional[Callable] = None,
                 dtype_fn: Optional[Callable] = None):
        self._f = getattr(cdll, symbol)
        self._f.restype = None
        self.n_inputs = n_inputs
        self.dtype = np.dtype(dtype)
        self.shape_fn = shape_fn
        self.dtype_fn = dtype_fn

    def _out_spec(self, shapes, dtypes):
        shape = tuple(self.shape_fn(*shapes)) if self.shape_fn \
            else tuple(shapes[0])
        dtype = np.dtype(self.dtype_fn(*dtypes)) if self.dtype_fn \
            else self.dtype
        return shape, dtype

    def _host(self, *arrays):
        if len(arrays) != self.n_inputs:
            raise TypeError(
                f"kernel takes {self.n_inputs} input(s), got {len(arrays)} "
                "(a wrong arity would pass garbage pointers to the C ABI)")
        # spec from PRE-cast dtypes so dtype_fn sees what the jit path's
        # tracer spec saw (the C kernel itself still computes in self.dtype)
        in_dtypes = [np.asarray(a).dtype for a in arrays]
        arrays = [np.ascontiguousarray(a, dtype=self.dtype) for a in arrays]
        for i, a in enumerate(arrays[1:], 1):
            if a.shape != arrays[0].shape:
                raise ValueError(
                    f"input {i} shape {a.shape} != input 0 shape "
                    f"{arrays[0].shape}: the C ABI passes input 0's shape "
                    "for all inputs (a mismatch would read past the smaller "
                    "buffer)")
        out_shape, out_dtype = self._out_spec(
            [a.shape for a in arrays], in_dtypes)
        out = np.zeros(out_shape, out_dtype)
        shape = np.asarray(arrays[0].shape, dtype=np.int64)
        argp = [a.ctypes.data_as(ctypes.c_void_p) for a in arrays]
        self._f(*argp, out.ctypes.data_as(ctypes.c_void_p),
                shape.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                ctypes.c_int64(len(shape)))
        return out

    def __call__(self, *arrays):
        if len(arrays) != self.n_inputs:
            raise TypeError(
                f"kernel takes {self.n_inputs} input(s), got {len(arrays)}")
        if not any(isinstance(a, jax.core.Tracer) for a in arrays):
            # eager: call the C kernel directly — works on every backend,
            # including plugins without host-callback support (axon)
            return jnp.asarray(self._host(*[np.asarray(a) for a in arrays]))
        out_shape, out_dtype = self._out_spec(
            [a.shape for a in arrays], [a.dtype for a in arrays])
        spec = jax.ShapeDtypeStruct(out_shape, out_dtype)
        return jax.pure_callback(self._host, spec, *arrays,
                                 vmap_method="sequential")


def load(name: str, sources=None, *, functions=None,
         extra_cflags: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None, verbose: bool = False,
         register: bool = True, vjps=None, dtype=np.float32,
         shape_fns=None, dtype_fns=None):
    """Compile C++ `sources` and expose exported kernels as framework ops
    (reference cpp_extension.load, python/paddle/utils/cpp_extension/
    cpp_extension.py:120).

    `functions`: {symbol_name: n_inputs} of C symbols to bridge (required —
    there is no ELF introspection here).  Each becomes a registered custom
    op named `symbol_name` (register=False returns plain callables instead).
    `vjps`: optional {symbol_name: vjp_fn} gradients.
    `shape_fns` / `dtype_fns`: optional {symbol_name: fn} output-spec
    inference — `shape_fn(*input_shapes) -> out_shape`,
    `dtype_fn(*input_dtypes) -> out_dtype` (reference SetInferShapeFn /
    SetInferDtypeFn, paddle/phi/api/ext/op_meta_info.h); without one the
    output mirrors input 0.

    Returns a namespace object with one attribute per function."""
    if not sources:
        raise ValueError("load() needs at least one C++ source file")
    if not functions:
        raise ValueError(
            "load() needs functions={symbol: n_inputs} naming the "
            "extern \"C\" kernels to expose")
    cdll = _compile(name, sources, extra_cflags, build_directory)

    class _NS:
        pass

    ns = _NS()
    for sym, n_in in functions.items():
        kern = _CppKernel(cdll, sym, n_in, dtype=dtype,
                          shape_fn=(shape_fns or {}).get(sym),
                          dtype_fn=(dtype_fns or {}).get(sym))
        if register:
            op = register_custom_op(sym, kern,
                                    vjp=(vjps or {}).get(sym),
                                    dtypes=(np.dtype(dtype).name,))
            setattr(ns, sym, op)
        else:
            setattr(ns, sym, kern)
    return ns
