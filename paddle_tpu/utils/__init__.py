"""paddle.utils parity surface (python/paddle/utils/__init__.py).

The load-bearing member is `cpp_extension` — the custom-kernel extension
API (reference `paddle/phi/api/ext/op_meta_info.h:943` PD_BUILD_OP +
`python/paddle/utils/cpp_extension/cpp_extension.py`), re-designed for TPU:
custom ops are Pallas/JAX functions (device path) or C++ host kernels
(compiled + bridged via jax.pure_callback), registered into the same op
table and dispatched through `apply_op` so tape/AMP/jit work unchanged.
"""

from . import cpp_extension  # noqa: F401
from .cpp_extension import CustomOp, get_custom_op, load, register_custom_op  # noqa: F401

__all__ = ["cpp_extension", "register_custom_op", "get_custom_op", "load",
           "CustomOp"]


def try_import(name):  # paddle.utils.try_import parity
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"Failed to import {name}: {e}") from e


def deprecated(update_to="", since="", reason="", level=0):
    """Deprecation decorator (reference utils/deprecated.py): warns once
    on first call, forwards to the wrapped function."""
    import functools
    import warnings

    def deco(fn):
        warned = []

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API '{fn.__name__}' is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level == 2:          # hard-removed: raise on EVERY call
                raise RuntimeError(msg)
            if not warned:
                warned.append(1)
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def require_version(min_version, max_version=None):
    """Version gate (reference utils/install_check.py require_version) —
    checks this package's version string."""
    from .. import __version__ as ver

    def key(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())
    if key(ver) < key(min_version):
        raise Exception(
            f"installed version {ver} < required minimum {min_version}")
    if max_version is not None and key(ver) > key(max_version):
        raise Exception(
            f"installed version {ver} > required maximum {max_version}")


def run_check():
    """Smoke-check the install (reference utils/install_check.py): one
    small matmul + grad on the default device, printing the verdict."""
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    y = x.matmul(x)
    y.sum().backward()
    assert x.grad is not None
    print("PaddlePaddle(TPU build) is installed successfully!")


try:
    from .. import __all__ as _pkg_all  # noqa: F401
    __all__ += ["deprecated", "require_version", "run_check"]
except Exception:  # pragma: no cover
    pass
