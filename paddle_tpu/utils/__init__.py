"""paddle.utils parity surface (python/paddle/utils/__init__.py).

The load-bearing member is `cpp_extension` — the custom-kernel extension
API (reference `paddle/phi/api/ext/op_meta_info.h:943` PD_BUILD_OP +
`python/paddle/utils/cpp_extension/cpp_extension.py`), re-designed for TPU:
custom ops are Pallas/JAX functions (device path) or C++ host kernels
(compiled + bridged via jax.pure_callback), registered into the same op
table and dispatched through `apply_op` so tape/AMP/jit work unchanged.
"""

from . import cpp_extension  # noqa: F401
from .cpp_extension import CustomOp, get_custom_op, load, register_custom_op  # noqa: F401

__all__ = ["cpp_extension", "register_custom_op", "get_custom_op", "load",
           "CustomOp"]


def try_import(name):  # paddle.utils.try_import parity
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"Failed to import {name}: {e}") from e
