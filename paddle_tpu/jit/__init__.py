"""paddle.jit parity — dynamic-to-static via XLA tracing.

Reference: python/paddle/jit (to_static AST transpiler + ProgramTranslator at
jit/dy2static/program_translator.py:313,1541; PartialProgramLayer executing a
captured Program via run_program).  TPU-native design: because every eager op
dispatches through a pure JAX function (tensor.py apply_op), *tracing the same
Python code under jax.jit* yields the static graph directly — no AST rewriting.
`to_static` functionalizes a Layer (params/buffers become jit inputs, threaded
through) and compiles with XLA; `TrainStep` additionally threads optimizer
state and donates buffers for in-place update performance (the analog of the
StandaloneExecutor steady-state hot loop, program_interpreter.cc:99).
"""

from __future__ import annotations

import functools
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..nn.layer import Layer
from ..tensor import Parameter, Tensor, to_tensor

__all__ = ["to_static", "not_to_static", "save", "load", "TrainStep", "ignore_module",
           "enable_to_static", "InputSpec", "TranslatedLayer",
           "set_verbosity", "set_code_level"]


class InputSpec:
    """paddle.static.InputSpec parity."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_arraylike(x):
    return isinstance(x, (jax.Array, np.ndarray)) or hasattr(x, "aval")


class _DynMarker:
    """Sentinel marking a traced-array position in a flattened arg list."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<dyn>"


_DYN = _DynMarker()


class _RngThread:
    """Thread a fresh RNG key through traced code (dropout etc.)."""

    def __init__(self):
        self._root = None

    def __call__(self, key):
        st = framework.get_state()
        self._prev = getattr(st, "trace_key", None)
        st.trace_key = key
        st.trace_key_count = 0
        return self

    def __enter__(self):
        return self

    def __exit__(self, *a):
        framework.get_state().trace_key = self._prev
        return False


class StaticFunction:
    """Compiled wrapper over a Layer.forward or plain function.

    Params/buffers are lifted to jit arguments (so weight updates between calls
    are respected), everything else traces as constants.
    """

    def __init__(self, function, input_spec=None, layer=None):
        self._fn = function
        self._layer = layer if layer is not None else getattr(function, "__self__", None)
        if not isinstance(self._layer, Layer):
            self._layer = None
        self._input_spec = input_spec
        # python-scalar specialization (dy2static parity: non-tensor args are
        # CONSTANTS of the traced program, so ints may drive shapes/ranges):
        # one compiled program per (train_mode, tree structure, static leaves)
        self._cache = {}

    @property
    def _params_and_buffers(self):
        if self._layer is None:
            return [], []
        params = [p for _, p in self._layer.named_parameters()]
        buffers = [b for _, b in self._layer.named_buffers() if b is not None]
        return params, buffers

    def _build(self, treedef, static_leaves):
        """Compile for one (tree structure, static python leaves) signature.
        `static_leaves[i] is _DYN` marks a traced array position."""
        fn = self._fn

        def pure(param_raws, buffer_raws, key, dyn_leaves):
            params, buffers = self._params_and_buffers
            old_p = [p._data for p in params]
            old_b = [b._data for b in buffers]
            st = framework.get_state()
            prev_key = getattr(st, "trace_key", None)
            st.trace_key = key
            st.trace_key_count = 0
            try:
                for p, r in zip(params, param_raws):
                    p._data = r
                for b, r in zip(buffers, buffer_raws):
                    b._data = r
                it = iter(dyn_leaves)
                leaves = [next(it) if s is _DYN else s for s in static_leaves]
                arg_raws, kwarg_raws = jax.tree_util.tree_unflatten(
                    treedef, leaves)
                args = jax.tree_util.tree_map(
                    lambda x: Tensor(x, stop_gradient=True) if _is_arraylike(x) else x, arg_raws,
                    is_leaf=_is_arraylike)
                kwargs = jax.tree_util.tree_map(
                    lambda x: Tensor(x, stop_gradient=True) if _is_arraylike(x) else x, kwarg_raws,
                    is_leaf=_is_arraylike)
                with framework.no_grad_guard():
                    out = fn(*args, **kwargs)
                out_raw = jax.tree_util.tree_map(
                    lambda x: x._data if isinstance(x, Tensor) else x, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                new_b = [b._data for b in buffers]
                return out_raw, new_b
            finally:
                for p, r in zip(params, old_p):
                    p._data = r
                for b, r in zip(buffers, old_b):
                    b._data = r
                st.trace_key = prev_key

        return jax.jit(pure)

    def __call__(self, *args, **kwargs):
        train_mode = self._layer.training if self._layer is not None else False
        arg_raws = jax.tree_util.tree_map(_unwrap, args, is_leaf=lambda x: isinstance(x, Tensor))
        kwarg_raws = jax.tree_util.tree_map(_unwrap, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
        leaves, treedef = jax.tree_util.tree_flatten((arg_raws, kwarg_raws))
        dyn_leaves = [l for l in leaves if _is_arraylike(l)]
        static_leaves = tuple(_DYN if _is_arraylike(l) else l for l in leaves)
        try:
            # include leaf types: 1, 1.0 and True hash equal but specialize
            # to different programs (dtype promotion differs)
            cache_key = (train_mode, treedef, static_leaves,
                         tuple(type(l) for l in static_leaves))
            hash(cache_key)
        except TypeError:  # unhashable static leaf: don't cache, just build
            cache_key = None
        jitted = self._cache.get(cache_key) if cache_key is not None else None
        if jitted is None:
            jitted = self._build(treedef, static_leaves)
            if cache_key is not None:
                if len(self._cache) >= 512:  # varying python scalars would
                    self._cache.pop(next(iter(self._cache)))  # leak programs
                self._cache[cache_key] = jitted
        params, buffers = self._params_and_buffers
        param_raws = [p._data for p in params]
        buffer_raws = [b._data for b in buffers]
        key = framework.next_rng_key()
        out_raw, new_b = jitted(param_raws, buffer_raws, key, dyn_leaves)
        for b, r in zip(buffers, new_b):
            b._data = r
        return jax.tree_util.tree_map(
            lambda x: Tensor(x) if _is_arraylike(x) else x, out_raw, is_leaf=_is_arraylike)

    # reference API compat
    def concrete_program(self):
        return None

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, **kwargs):
    """Decorator/wrapper: compile a function or Layer with XLA."""

    def decorate(fn):
        if isinstance(fn, Layer):
            static = StaticFunction(fn.forward, input_spec, layer=fn)
            fn.forward = static
            return fn
        if getattr(fn, "_not_to_static", False):
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(function):
    function._not_to_static = True
    return function


def ignore_module(modules):
    return None


def enable_to_static(flag: bool):
    framework.get_state().flags["FLAGS_enable_to_static"] = flag


class TrainStep:
    """Fully-compiled training step: forward + backward + optimizer update in ONE
    XLA executable with donated param/opt-state buffers.

    This is the TPU hot path (reference analog: the whole dygraph step —
    python_c shim → ad_func → kernels → backward.cc → optimizer — collapsed
    into one compiled program).  Usage:

        step = TrainStep(model, loss_fn, opt)       # loss_fn(model, *batch)
        loss = step(x, y)                           # updates model in place
    """

    def __init__(self, model: Layer, loss_fn, optimizer, donate=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._params = [p for _, p in model.named_parameters() if p.trainable]
        self._buffers = [b for _, b in model.named_buffers() if b is not None]
        self._opt_state = optimizer.functional_init([p._data for p in self._params])
        self._jitted = None
        self._root_key = jax.random.PRNGKey(framework.default_generator().initial_seed() or 0)
        self._step_i = 0
        self._donate = donate

    def _build(self):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        params, buffers = self._params, self._buffers

        def pure(param_raws, opt_state, buffer_raws, key, lr, arg_raws):
            def loss_of(p_raws):
                old_p = [p._data for p in params]
                old_b = [b._data for b in buffers]
                st = framework.get_state()
                prev_key = getattr(st, "trace_key", None)
                st.trace_key = key
                st.trace_key_count = 0
                try:
                    for p, r in zip(params, p_raws):
                        p._data = r
                    for b, r in zip(buffers, buffer_raws):
                        b._data = r
                    args = jax.tree_util.tree_map(
                        lambda x: Tensor(x, stop_gradient=True) if _is_arraylike(x) else x,
                        arg_raws, is_leaf=_is_arraylike)
                    with framework.no_grad_guard():
                        loss = loss_fn(model, *args)
                    new_b = [b._data for b in buffers]
                    return loss._data, new_b
                finally:
                    for p, r in zip(params, old_p):
                        p._data = r
                    for b, r in zip(buffers, old_b):
                        b._data = r
                    st.trace_key = prev_key

            (loss_raw, new_b), grads = jax.value_and_grad(loss_of, has_aux=True)(list(param_raws))
            new_params, new_opt_state = optimizer.functional_apply(param_raws, grads, opt_state, lr=lr)
            return new_params, new_opt_state, new_b, loss_raw

        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(pure, donate_argnums=donate)

    def __call__(self, *batch):
        if self._jitted is None:
            self._jitted = self._build()
        arg_raws = jax.tree_util.tree_map(_unwrap, batch, is_leaf=lambda x: isinstance(x, Tensor))
        self._step_i += 1
        key = jax.random.fold_in(self._root_key, self._step_i)
        lr = jnp.asarray(self.optimizer.get_lr(), dtype=jnp.float32)
        param_raws = [p._data for p in self._params]
        buffer_raws = [b._data for b in self._buffers]
        new_params, self._opt_state, new_b, loss_raw = self._jitted(
            param_raws, self._opt_state, buffer_raws, key, lr, arg_raws)
        for p, r in zip(self._params, new_params):
            p._data = r
        for b, r in zip(self._buffers, new_b):
            b._data = r
        if isinstance(self.optimizer._lr, object) and hasattr(self.optimizer._lr, "step") and not isinstance(self.optimizer._lr, (int, float)):
            pass  # scheduler stepping is the caller's choice (paddle parity)
        return Tensor(loss_raw)


# ---------------------------------------------------------------------------
# jit.save / jit.load (inference model export)
# ---------------------------------------------------------------------------


def save(layer, path, input_spec=None, **configs):
    """Saves params + (when possible) a StableHLO export of forward.

    Reference: jit/api.py save → inference model.  TPU-native: the portable
    artifact is StableHLO (jax.export), the params a pickled state dict.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {}
    if isinstance(layer, Layer):
        for k, v in layer.state_dict().items():
            state[k] = np.asarray(v._data)
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    meta = {"class": type(layer).__name__, "input_spec": None}
    if input_spec is not None:
        meta["input_spec"] = [
            {"shape": s.shape, "dtype": str(s.dtype), "name": s.name} if isinstance(s, InputSpec)
            else {"shape": list(s.shape), "dtype": str(s.dtype), "name": None}
            for s in input_spec
        ]
        # StableHLO export of the forward graph
        try:
            from jax import export as jax_export

            fn = layer.forward if isinstance(layer, Layer) else layer
            static = fn if isinstance(fn, StaticFunction) else StaticFunction(
                fn, layer=layer if isinstance(layer, Layer) else None)
            params, buffers = static._params_and_buffers
            args_abs = [
                jax.ShapeDtypeStruct(tuple(d if d is not None and d != -1 else 1 for d in s.shape),
                                     framework.to_jax_dtype(framework.convert_dtype(s.dtype)))
                for s in input_spec
            ]

            def pure_infer(*arg_raws):
                param_raws = [p._data for p in params]
                buffer_raws = [b._data for b in buffers]
                key = jax.random.PRNGKey(0)
                leaves, treedef = jax.tree_util.tree_flatten(
                    (tuple(arg_raws), {}))
                jitted = static._build(treedef,
                                       tuple(_DYN for _ in leaves))
                out, _ = jitted(param_raws, buffer_raws, key, leaves)
                return out

            exported = jax_export.export(jax.jit(pure_infer))(*args_abs)
            with open(path + ".stablehlo", "wb") as f:
                f.write(exported.serialize())
            meta["stablehlo"] = True
        except Exception as e:  # noqa: BLE001
            meta["stablehlo"] = False
            meta["export_error"] = str(e)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """Loaded inference layer (reference: jit/translated_layer.py)."""

    def __init__(self, state, meta, path):
        super().__init__()
        self._state = state
        self._meta = meta
        self._exported = None
        self._call = None
        if meta.get("stablehlo"):
            from jax import export as jax_export

            with open(path + ".stablehlo", "rb") as f:
                self._exported = jax_export.deserialize(f.read())
            # jit the exported call ONCE: repeat runs reuse the compiled
            # executable, and the compile lands in jax's (optionally
            # persistent — inference.Config.set_optim_cache_dir) cache
            self._call = jax.jit(self._exported.call)

    def forward(self, *args):
        if self._exported is None:
            raise RuntimeError("no compiled graph saved; re-save with input_spec")
        raws = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._call(*raws)
        return jax.tree_util.tree_map(lambda x: Tensor(x), out)

    def state_dict(self, *a, **k):
        return {k2: to_tensor(v) for k2, v in self._state.items()}


def load(path, **configs):
    with open(path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    try:
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
    except FileNotFoundError:
        meta = {}
    return TranslatedLayer(state, meta, path)


_VERBOSITY = 0
_CODE_LEVEL = 0


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static logging verbosity (reference jit/dy2static/logging_utils).
    The record-replay translator has no transformation passes to log, so
    this stores the level for API parity."""
    global _VERBOSITY
    _VERBOSITY = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """Level of transformed-code dumping (reference parity; see
    set_verbosity)."""
    global _CODE_LEVEL
    _CODE_LEVEL = int(level)
