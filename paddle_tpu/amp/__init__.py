"""AMP (python/paddle/amp/ parity: auto_cast.py:270 amp_guard, grad_scaler.py).

TPU-native: bfloat16 is the native MXU dtype, so O1/O2 with dtype='bfloat16'
needs no loss scaling at all (GradScaler becomes a transparent pass-through by
default, matching how the reference's scaler disables itself for bf16).  The
cast hooks live in tensor.apply_op (the dispatch point), mirroring the
reference's eager_amp_auto_cast.h insertion in the generated ad_func.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from .. import framework
from ..tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate", "GradScaler", "AmpScaler",
           "is_float16_supported", "is_bfloat16_supported", "white_list", "black_list"]


class _AmpState:
    def __init__(self, enable, dtype, level, custom_white_list, custom_black_list):
        self.enable = enable
        self.dtype = dtype
        self.level = level
        self.custom_white_list = frozenset(custom_white_list or ())
        self.custom_black_list = frozenset(custom_black_list or ())


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast parity (dtype defaults to bfloat16 on TPU)."""
    st = framework.get_state()
    prev = st.amp_state
    st.amp_state = _AmpState(enable, dtype, level, custom_white_list, custom_black_list) if enable else None
    try:
        yield
    finally:
        st.amp_state = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None,
             save_dtype=None, master_grad=False, excluded_layers=None):
    """O2: cast model params to low precision + enable master weights."""
    from ..nn.layer import Layer

    single_model = isinstance(models, Layer)
    models_list = [models] if single_model else list(models)
    if level == "O2":
        excluded = excluded_layers or []
        for m in models_list:
            for layer in m.sublayers(include_self=True):
                if any(isinstance(layer, e if isinstance(e, type) else type(e)) for e in excluded):
                    continue
                # keep norms in fp32 (reference O2 behavior)
                from ..nn.common import LayerNorm, RMSNorm, _BatchNormBase
                if isinstance(layer, (LayerNorm, RMSNorm, _BatchNormBase)):
                    continue
                for p in layer._parameters.values():
                    if p is not None and framework.is_floating_dtype(p.dtype):
                        p._data = p._data.astype(framework.to_jax_dtype(dtype))
        if optimizers is not None:
            opts = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
            for o in opts:
                o._multi_precision = True if master_weight is None else master_weight
    if optimizers is None:
        return models if single_model else models_list
    return (models if single_model else models_list), optimizers


amp_decorate = decorate


class GradScaler:
    """Dynamic loss scaling (python/paddle/amp/grad_scaler.py:576 parity).

    On TPU with bf16 this is a pass-through (enable=False is the sane default
    there); for fp16 experiments the full dynamic-scale algorithm is active.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0**16, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        # id(optimizer) -> {"state": "unscaled" | "stepped",
        #                   "found_inf": bool}; absent = initial.  Mirrors
        # the reference's per-optimizer _optimizer_states so one scaler can
        # drive several optimizers per iteration (GAN pattern) — each
        # optimizer's step() is skipped ONLY by its own overflow
        # (grad_scaler.py:341 resets _found_inf per _unscale)
        self._opt_state: dict = {}
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _do_unscale(self, optimizer):
        """Unscale this optimizer's grads; records found_inf PER OPTIMIZER
        (one optimizer's overflow must not skip another's step — the GAN
        two-optimizer pattern)."""
        import jax.numpy as jnp
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        for p in params:
            if p.grad is None:
                continue
            p.grad._data = p.grad._data * inv
        finite = [jnp.all(jnp.isfinite(p.grad._data)) for p in params if p.grad is not None]
        found = bool(finite) and not bool(jnp.all(jnp.stack(finite)))
        self._opt_state.setdefault(id(optimizer), {})["found_inf"] = found
        # update()'s scale decision: OR over the optimizers unscaled this
        # iteration (the scale is shared, so ANY overflow means it is too
        # high — documented convention; the reference keys off the last
        # unscale, which under-reacts when only an earlier one overflowed)
        self._found_inf = self._found_inf or found

    def unscale_(self, optimizer):
        if not self._enable:
            return
        st = self._opt_state.get(id(optimizer), {}).get("state")
        if st == "unscaled":
            raise RuntimeError(
                "unscale_() has already been called since the last update().")
        if st == "stepped":
            raise RuntimeError("unscale_() is being called after step().")
        self._do_unscale(optimizer)
        self._opt_state[id(optimizer)]["state"] = "unscaled"

    def step(self, optimizer):
        """Reference grad_scaler.py:716 — step() only applies (or skips) the
        optimizer update; the loss-scale adjustment happens in the SEPARATE
        update() call.  Grads are unscaled once per optimizer per iteration
        (an explicit prior unscale_() is honored, not repeated), a second
        step() on the same optimizer without update() raises, and the skip
        decision consults only THIS optimizer's found_inf."""
        if not self._enable:
            optimizer.step()
            return
        st = self._opt_state.get(id(optimizer), {}).get("state")
        if st == "stepped":
            raise RuntimeError(
                "step() has already been called since the last update().")
        if st is None:
            self._do_unscale(optimizer)
        if not self._opt_state[id(optimizer)]["found_inf"]:
            optimizer.step()
        self._opt_state[id(optimizer)]["state"] = "stepped"

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        self._opt_state.clear()
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)

    set_state_dict = load_state_dict


AmpScaler = GradScaler


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


def white_list():
    from ..tensor import _AMP_WHITE
    return {"float16": {"O1": set(_AMP_WHITE), "O2": set(_AMP_WHITE)},
            "bfloat16": {"O1": set(_AMP_WHITE), "O2": set(_AMP_WHITE)}}


def black_list():
    from ..tensor import _AMP_BLACK
    return {"float16": {"O1": set(_AMP_BLACK), "O2": set(_AMP_BLACK)},
            "bfloat16": {"O1": set(_AMP_BLACK), "O2": set(_AMP_BLACK)}}


def debugging_enable_operator_stats_collection():
    return None


def debugging_disable_operator_stats_collection():
    return None
