"""Multi-tenant QoS: tenant config, weighted-fair admission, priority tiers.

Production traffic is not one queue.  A single flooding client on a FIFO
engine degrades every other client's p99 identically; the fix is to make
every contended resource *priority-aware* while keeping tenancy entirely
OUTSIDE the compiled programs (the fixed-shape ragged dispatch never sees
a tenant label — all of this is host-side scheduling).

Three pieces live here:

``TenantConfig``
    One tenant's share of the engine: a WFQ ``weight`` (relative service
    share among same-priority tenants), a ``priority`` tier (LOWER number
    = MORE important; tier 0 preempts tier 1 work under pressure), and an
    optional per-tenant ``max_pending`` queue cap so ``QueueFull`` is a
    per-tenant verdict rather than a fleet-wide one.

``QoSPolicy``
    The tenant table plus resolution rules.  Explicitly configured
    policies are STRICT: an unknown tenant label raises ``UnknownTenant``
    (a ``ValueError``, so the serve paths map it to HTTP 400).  The
    default policy (engine built with ``tenants=None``) auto-vivifies a
    config per new label so single-tenant deployments pay nothing.  A
    request may ask for a priority, but it is clamped to
    ``max(request_priority, tenant.priority)`` — a tenant cannot claim
    more importance than its table row grants.

``WFQQueue``
    The engine's pending queue: per-tenant FIFO deques selected by
    (priority tier asc, virtual time asc).  Each tenant's virtual time
    advances by ``cost / weight`` when one of its requests is admitted
    (cost = prompt tokens + max_new_tokens — the work the request can
    consume), so a 2x-weight tenant drains twice the tokens per unit of
    virtual time.  A tenant going from idle to active has its clock
    jumped forward to the minimum active virtual time so it cannot bank
    service while idle and then starve everyone with the accumulated
    credit.  The class is deque-API compatible (``append``,
    ``appendleft``, ``popleft``, ``remove``, ``clear``, ``[0]`` peek,
    iteration, ``len``/``bool``) because the engine's invariant checkers
    and cancellation path treat ``engine._pending`` as a deque.
    ``appendleft`` feeds a separate RESUME lane with absolute precedence:
    preempted requests already paid their queueing (and their virtual
    time) once, so they re-enter at the head regardless of tenant clocks.

threadlint: every mutating method on ``WFQQueue`` must be called under
``LLMEngine._cv`` — the class adds no lock of its own, exactly like the
deque it replaces.
"""

from __future__ import annotations

import collections
import math
from typing import Dict, Iterable, Optional

__all__ = [
    "DEFAULT_TENANT",
    "TenantConfig",
    "UnknownTenant",
    "QoSPolicy",
    "WFQQueue",
]

DEFAULT_TENANT = "default"


class UnknownTenant(ValueError):
    """A request named a tenant the strict policy has no row for.

    Subclasses ``ValueError`` so the HTTP serve paths map it to a 400
    without a dedicated handler."""

    def __init__(self, tenant: str):
        super().__init__(f"unknown tenant {tenant!r}")
        self.tenant = str(tenant)


class TenantConfig:
    """One tenant's QoS row (see module doc).  ``priority`` is a tier:
    lower number = more important.  ``weight`` must be positive;
    ``max_pending`` of None defers to the engine-wide cap."""

    __slots__ = ("name", "weight", "priority", "max_pending")

    def __init__(self, name: str, weight: float = 1.0, priority: int = 1,
                 max_pending: Optional[int] = None):
        self.name = str(name)
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        self.weight = float(weight)
        if not math.isfinite(self.weight) or self.weight <= 0.0:
            raise ValueError(
                f"tenant {name!r}: weight must be finite and > 0, "
                f"got {weight!r}")
        self.priority = int(priority)
        if self.priority < 0:
            raise ValueError(
                f"tenant {name!r}: priority must be >= 0, got {priority!r}")
        if max_pending is not None:
            max_pending = int(max_pending)
            if max_pending < 1:
                raise ValueError(
                    f"tenant {name!r}: max_pending must be >= 1, "
                    f"got {max_pending!r}")
        self.max_pending = max_pending

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TenantConfig({self.name!r}, weight={self.weight}, "
                f"priority={self.priority}, max_pending={self.max_pending})")


class QoSPolicy:
    """Tenant table + label resolution (see module doc)."""

    def __init__(self, tenants: Optional[Iterable[TenantConfig]] = None,
                 strict: Optional[bool] = None):
        self._tenants: Dict[str, TenantConfig] = {}
        explicit = tenants is not None
        for cfg in (tenants or ()):
            if not isinstance(cfg, TenantConfig):
                raise TypeError(
                    f"tenants must be TenantConfig instances, got {cfg!r}")
            if cfg.name in self._tenants:
                raise ValueError(f"duplicate tenant {cfg.name!r}")
            self._tenants[cfg.name] = cfg
        # Explicit tables are strict: a label outside the table is a
        # client error, not an invitation to mint a row.  The implicit
        # single-tenant policy auto-vivifies instead.
        self.strict = bool(strict) if strict is not None else explicit
        # The default tenant ALWAYS exists, strict or not: untagged
        # traffic (router canaries, invariant probes, legacy clients)
        # resolves to it — strictness rejects unknown NAMED tenants, it
        # must not reject the absence of a name.  An explicit table may
        # still override the default row's weight/priority/cap.
        if DEFAULT_TENANT not in self._tenants:
            self._tenants[DEFAULT_TENANT] = TenantConfig(DEFAULT_TENANT)

    @classmethod
    def build(cls, spec) -> "QoSPolicy":
        """Coerce the engine's ``tenants=`` kwarg: an existing policy, an
        iterable of ``TenantConfig``, a ``{name: dict-of-kwargs}``
        mapping, or None (implicit single-tenant)."""
        if spec is None:
            return cls()
        if isinstance(spec, QoSPolicy):
            return spec
        if isinstance(spec, dict):
            rows = []
            for name, kw in spec.items():
                if isinstance(kw, TenantConfig):
                    rows.append(kw)
                else:
                    rows.append(TenantConfig(name, **dict(kw or {})))
            return cls(rows)
        return cls(list(spec))

    def tenants(self) -> Dict[str, TenantConfig]:
        return dict(self._tenants)

    def get(self, name: str) -> TenantConfig:
        cfg = self._tenants.get(str(name))
        if cfg is None:
            if self.strict:
                raise UnknownTenant(str(name))
            cfg = TenantConfig(str(name))
            self._tenants[str(name)] = cfg
        return cfg

    def resolve(self, tenant, priority):
        """Resolve a request's (tenant, priority) labels to
        ``(name, effective_priority, TenantConfig)``.

        ``None`` tenant maps to the default label.  A request priority is
        clamped to ``max(request, tenant.priority)`` — requests can make
        themselves LESS important than their tenant tier, never more."""
        name = DEFAULT_TENANT if tenant is None else str(tenant)
        if not name:
            raise ValueError("tenant must be a non-empty string")
        cfg = self.get(name)
        if priority is None:
            eff = cfg.priority
        else:
            try:
                eff = int(priority)
            except (TypeError, ValueError):
                raise ValueError(
                    f"priority must be an integer, got {priority!r}")
            if eff < 0:
                raise ValueError(f"priority must be >= 0, got {priority!r}")
            eff = max(eff, cfg.priority)
        return name, eff, cfg


def _cost(req) -> int:
    """Virtual-time cost of admitting one request: the tokens it can
    consume (prompt prefill + generation budget)."""
    try:
        return max(1, int(req.prompt.size) + int(req.max_new_tokens))
    except Exception:  # noqa: BLE001 - foreign request objects cost 1
        return 1


class WFQQueue:
    """Weighted-fair pending queue, deque-API compatible (module doc).

    threadlint: caller holds ``LLMEngine._cv`` for every method."""

    def __init__(self, policy: Optional[QoSPolicy] = None):
        self.policy = policy or QoSPolicy()
        self._resume: collections.deque = collections.deque()
        self._queues: Dict[str, collections.deque] = {}
        self._vtime: Dict[str, float] = {}
        self._resume_counts: Dict[str, int] = {}

    # -- sizing / iteration (checker + digest surface) ----------------------

    def __len__(self) -> int:
        return len(self._resume) + sum(
            len(q) for q in self._queues.values())

    def __bool__(self) -> bool:
        if self._resume:
            return True
        return any(self._queues.values())

    def __iter__(self):
        # Resume lane first (it pops first), then tenants in table order.
        for r in self._resume:
            yield r
        for q in self._queues.values():
            yield from q

    def __getitem__(self, idx):
        # The engine only ever peeks the head ([0]); it must agree with
        # what the next popleft returns.
        if idx != 0:
            raise IndexError("WFQQueue supports head peek only")
        head = self._peek()
        if head is None:
            raise IndexError("peek from an empty WFQQueue")
        return head

    # -- tenant bookkeeping --------------------------------------------------

    def _tenant_of(self, req) -> str:
        return getattr(req, "tenant", DEFAULT_TENANT) or DEFAULT_TENANT

    def depth(self, tenant: str) -> int:
        """Pending requests carrying this tenant label, resume lane
        included (the per-tenant queue-depth gauge and cap check)."""
        q = self._queues.get(tenant)
        return (len(q) if q is not None else 0) \
            + self._resume_counts.get(tenant, 0)

    def depths(self) -> Dict[str, int]:
        out = {t: len(q) for t, q in self._queues.items() if q}
        for t, n in self._resume_counts.items():
            if n:
                out[t] = out.get(t, 0) + n
        return out

    def virtual_times(self) -> Dict[str, float]:
        return dict(self._vtime)

    # -- deque API -----------------------------------------------------------

    def append(self, req) -> None:
        t = self._tenant_of(req)
        q = self._queues.get(t)
        if q is None:
            q = self._queues[t] = collections.deque()
        if not q:
            # Idle -> active: jump the clock forward to the minimum
            # active virtual time so idle periods bank no credit.
            active = [self._vtime[o] for o, oq in self._queues.items()
                      if oq and o != t and o in self._vtime]
            floor = min(active) if active else 0.0
            self._vtime[t] = max(self._vtime.get(t, 0.0), floor)
        q.append(req)

    def appendleft(self, req) -> None:
        # Preemption resume lane: already admitted once, already charged
        # to its tenant's clock — absolute precedence, no re-billing.
        t = self._tenant_of(req)
        self._resume.appendleft(req)
        self._resume_counts[t] = self._resume_counts.get(t, 0) + 1

    def _select(self) -> Optional[str]:
        """The tenant the next popleft serves: lowest priority tier
        first (lower number = more important), then lowest virtual time,
        then name for determinism."""
        best = None
        best_key = None
        for t, q in self._queues.items():
            if not q:
                continue
            head = q[0]
            key = (int(getattr(head, "priority", 1)),
                   self._vtime.get(t, 0.0), t)
            if best_key is None or key < best_key:
                best, best_key = t, key
        return best

    def _peek(self):
        if self._resume:
            return self._resume[0]
        t = self._select()
        return self._queues[t][0] if t is not None else None

    def popleft(self):
        if self._resume:
            req = self._resume.popleft()
            t = self._tenant_of(req)
            n = self._resume_counts.get(t, 0) - 1
            if n > 0:
                self._resume_counts[t] = n
            else:
                self._resume_counts.pop(t, None)
            return req
        t = self._select()
        if t is None:
            raise IndexError("pop from an empty WFQQueue")
        req = self._queues[t].popleft()
        weight = self.policy.get(t).weight
        self._vtime[t] = self._vtime.get(t, 0.0) + _cost(req) / weight
        return req

    def remove(self, req) -> None:
        """Remove a specific request (cancellation path).  Raises
        ``ValueError`` when absent, exactly like ``deque.remove`` —
        ``_Request.cancel`` relies on that to fall back to slot-side
        cancellation."""
        try:
            self._resume.remove(req)
        except ValueError:
            pass
        else:
            t = self._tenant_of(req)
            n = self._resume_counts.get(t, 0) - 1
            if n > 0:
                self._resume_counts[t] = n
            else:
                self._resume_counts.pop(t, None)
            return
        t = self._tenant_of(req)
        q = self._queues.get(t)
        if q is not None:
            try:
                q.remove(req)
                return
            except ValueError:
                pass
        # Label drifted (foreign req object): scan every lane before
        # declaring it absent.
        for q in self._queues.values():
            try:
                q.remove(req)
                return
            except ValueError:
                continue
        raise ValueError("request not in pending queue")

    def clear(self) -> None:
        self._resume.clear()
        self._resume_counts.clear()
        for q in self._queues.values():
            q.clear()
