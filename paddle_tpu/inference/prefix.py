"""Radix prefix index: cross-user KV reuse over the refcounted page pool.

Millions of requests share a handful of system prompts and few-shot
preambles, yet a cache-less engine re-prefills them from token zero every
time.  This index remembers WHERE a prefix's KV already lives: a radix
tree keyed on token ids with PAGE-GRANULAR nodes — every node is exactly
one page of the `PagedKVCache` pool, carrying the token ids cached in it
(a full `page_size` tokens for interior nodes; the last node of an
inserted prefix may be partial).  The index holds ONE refcount on each
node's page (`cache.add_ref`), so a cached prefix survives the slot that
computed it.

Admission lookup walks the tree for the longest cached prefix of a new
prompt and returns its page chain; the engine then SPLICES those pages
into the fresh slot (`cache.splice_pages` — refcount bookkeeping only, no
dispatch) and chunk-prefills just the unshared suffix.  A lookup may
claim a node partially (the first j of its tokens): the page holds valid
KV for every cached position and the kernel's ctx_len masking never reads
past the claimed length.  Matches are capped at `max_tokens` (callers
pass len(prompt) - 1: at least one token must prefill so the finishing
span has logits to sample from).

Insertion happens when a slot finishes prefilling: its pages become
nodes.  Pages already cached for the same tokens are deduped (the slot
keeps its own copy; it frees on release); a partial node is UPGRADED in
place when a longer insert extends it (the index swaps to the fuller
page and drops its ref on the old one — co-holding slots keep it alive
until they release).

Eviction is LRU over evictable leaves, and only under page pressure —
the engine calls `evict(n)` when allocation fails before it considers
preempting a live sequence.  A leaf is evictable iff the index is its
page's ONLY holder (refcount 1); evicting it returns the page to the
free pool, and may expose its parent as the next evictable leaf.
`clear()` drops every reference — the engine calls it when the pools are
deallocated/re-zeroed (`_recover_pools`), because a cached prefix must
never outlive the KV it points at.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PrefixIndex"]


class _Node:
    """One cached page: `tokens` (the ids cached in it, oldest first),
    `page` (its pool page id), children keyed by their full token tuple,
    an LRU clock stamp, and a QoS `tier` (the lowest priority number —
    i.e. the MOST important tenant — that ever cached or re-cached this
    prefix; eviction drains high-number tiers first)."""

    __slots__ = ("tokens", "page", "children", "parent", "last_used",
                 "tier")

    def __init__(self, tokens: tuple, page: int,
                 parent: Optional["_Node"], tier: int = 1):
        self.tokens = tokens
        self.page = int(page)
        self.children: dict = {}
        self.parent = parent
        self.last_used = 0
        self.tier = int(tier)

    @property
    def n_tokens(self) -> int:
        return len(self.tokens)


class PrefixIndex:
    """Page-granular radix tree over a `PagedKVCache` (see module doc)."""

    def __init__(self, cache, on_evict=None):
        self._cache = cache
        self.page_size = int(cache.page_size)
        self._root: dict = {}            # token tuple -> _Node
        self._by_page: dict = {}         # page id -> _Node
        self._clock = 0
        self.evicted_pages_total = 0
        # demotion hook: called with the node being dropped WHILE its
        # page's KV is still valid (before the index's ref is released),
        # and only when the index is the page's last holder — the tiered
        # host store (inference/kvstore.py) copies the page out here.
        # `clear()` deliberately bypasses it: pool recovery drops dead
        # KV, and demoting garbage would serve silent corruption later.
        self.on_evict = on_evict

    # -- introspection ------------------------------------------------------

    @property
    def cached_pages(self) -> int:
        return len(self._by_page)

    @property
    def node_count(self) -> int:
        return len(self._by_page)

    @property
    def leaf_count(self) -> int:
        """Distinct cached prefixes (chains sharing pages count once per
        ENDPOINT): the /stats "cached_prefixes" figure."""
        return sum(1 for n in self._by_page.values() if not n.children)

    def pages(self) -> set:
        """The set of pool pages the index currently holds a ref on."""
        return set(self._by_page)

    def page_refs(self) -> dict:
        """page -> index-held reference count (always 1 per cached page;
        the invariant checker joins this with slot page lists against
        `cache._refcount`)."""
        return {p: 1 for p in self._by_page}

    def first_chunks(self) -> tuple:
        """Token tuples of the FULL-page root children — the per-replica
        prefix digest the Router's affinity score matches request heads
        against.  Partial root nodes (a cached prompt shorter than one
        page) are excluded: the engine's splice floor treats sub-page
        matches as misses, so steering traffic toward them would
        discount load for zero benefit."""
        return tuple(t for t in self._root if len(t) == self.page_size)

    # -- lookup / insert ----------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def lookup(self, tokens, max_tokens: int) -> Tuple[int, List[int]]:
        """Longest cached prefix of `tokens`, capped at `max_tokens`.
        Returns (matched_token_count, page_chain); (0, []) on a miss.
        The last page of the chain may be claimed partially (matched not
        page-aligned) — the splicing slot must copy-on-write it before
        appending.  Every node on the hit path is LRU-touched."""
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        limit = min(int(max_tokens), len(toks))
        matched = 0
        pages: List[int] = []
        children = self._root
        now = self._tick()
        while matched < limit:
            best = None
            best_common = 0
            chunk = toks[matched:matched + self.page_size]
            exact = children.get(tuple(chunk))
            if exact is not None and matched + exact.n_tokens <= limit:
                best, best_common = exact, exact.n_tokens
            else:
                for node in children.values():
                    common = 0
                    cap = min(node.n_tokens, limit - matched)
                    for a, b in zip(node.tokens[:cap], chunk):
                        if a != b:
                            break
                        common += 1
                    if common > best_common:
                        best, best_common = node, common
            if best is None or best_common == 0:
                break
            best.last_used = now
            pages.append(best.page)
            matched += best_common
            if best_common < best.n_tokens or best.n_tokens < self.page_size:
                break               # partial claim / partial node: no deeper
            children = best.children
        return matched, pages

    def insert(self, tokens, n_tokens: int, pages: Sequence[int],
               tier: int = 1) -> int:
        """Register a freshly prefilled prefix: `tokens[:n_tokens]` is
        cached in `pages` (page i holds tokens [i*ps, (i+1)*ps)).  Walks
        the tree creating nodes for uncached pages (taking one refcount
        each), dedupes against existing ones, and upgrades a partial node
        when this insert extends it.  Returns the number of pages newly
        referenced by the index.  `tier` is the inserting request's QoS
        priority (lower = more important); a node shared across tiers
        keeps its MOST important one, so a prefix a premium tenant also
        uses never evicts on a flooding tenant's ladder rung."""
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        n_tokens = min(int(n_tokens), len(toks))
        tier = int(tier)
        children = self._root
        parent = None
        added = 0
        now = self._tick()
        pos = 0
        for page in pages:
            n = min(self.page_size, n_tokens - pos)
            if n <= 0:
                break
            chunk = tuple(toks[pos:pos + n])
            node = children.get(chunk)
            if node is None:
                # a partial node this chunk extends? upgrade it in place:
                # swap the index's ref to the fuller page; co-holding
                # slots keep the old page alive until they release it
                partial = next(
                    (c for c in children.values()
                     if c.n_tokens < n and chunk[:c.n_tokens] == c.tokens),
                    None)
                if partial is not None:
                    del children[partial.tokens]
                    del self._by_page[partial.page]
                    self._cache.add_ref(page)
                    self._cache.drop_ref(partial.page)
                    partial.tokens = chunk
                    partial.page = int(page)
                    children[chunk] = partial
                    self._by_page[int(page)] = partial
                    node = partial
                    added += 1
                else:
                    # an existing LONGER node already covers this chunk?
                    # nothing to add (we cannot hang children off a
                    # partial insert anyway)
                    covered = any(
                        c.n_tokens >= n and c.tokens[:n] == chunk
                        for c in children.values())
                    if covered:
                        break
                    node = _Node(chunk, page, parent, tier=tier)
                    self._cache.add_ref(page)
                    children[chunk] = node
                    self._by_page[int(page)] = node
                    added += 1
            node.last_used = now
            # shared across tiers: keep the most important claimant
            node.tier = min(node.tier, tier)
            if node.n_tokens < self.page_size:
                break               # partial tail: nothing hangs below it
            children = node.children
            parent = node
            pos += n
        return added

    # -- eviction -----------------------------------------------------------

    def _drop_node(self, node: _Node) -> bool:
        """Remove one childless node, releasing the index's page ref.
        Returns True iff the page went back to the free pool."""
        siblings = node.parent.children if node.parent is not None \
            else self._root
        del siblings[node.tokens]
        del self._by_page[node.page]
        self.evicted_pages_total += 1
        if self.on_evict is not None \
                and self._cache.refcount(node.page) == 1:
            # last holder: the page frees on the drop_ref below, so this
            # is the only moment its KV can still be demoted.  A shared
            # page (a live slot co-holds it) survives anyway — demoting
            # it too would just duplicate bytes the device still serves.
            try:
                self.on_evict(node)
            except Exception:  # noqa: BLE001 — demotion is best-effort;
                pass           # eviction must free the page regardless
        return self._cache.drop_ref(node.page)

    def full_prefix(self, node: _Node) -> tuple:
        """The token prefix from the root through `node` (the tiered
        store's key for this node's page)."""
        chain: List[tuple] = []
        n: Optional[_Node] = node
        while n is not None:
            chain.append(n.tokens)
            n = n.parent
        out: tuple = ()
        for t in reversed(chain):
            out = out + t
        return out

    def evict(self, n_pages: int) -> int:
        """Tier-then-LRU evict unreferenced cached prefixes until
        `n_pages` pages returned to the free pool (or nothing evictable
        remains).  The eviction ladder drains the LEAST important QoS
        tier first (highest tier number — see _Node.tier), and only
        within a tier falls back to LRU — a premium tenant's warm
        prefixes survive a flooding tenant's page pressure.  Only
        leaves whose page the index holds EXCLUSIVELY (refcount 1) are
        candidates — a prefix a live slot still reads is never evicted;
        dropping a leaf may expose its parent next (pushed onto the
        candidate heap, so one call scans the index ONCE rather than
        once per freed page — this runs on the admission hot path).
        Returns pages actually freed to the pool."""
        heap = [(-n.tier, n.last_used, n.page, n)
                for n in self._by_page.values()
                if not n.children and self._cache.refcount(n.page) == 1]
        heapq.heapify(heap)
        freed = 0
        while heap and freed < n_pages:
            _, _, _, node = heapq.heappop(heap)
            if self._by_page.get(node.page) is not node or node.children \
                    or self._cache.refcount(node.page) != 1:
                continue            # stale heap entry
            parent = node.parent
            if self._drop_node(node):
                freed += 1
            if parent is not None and not parent.children \
                    and self._by_page.get(parent.page) is parent \
                    and self._cache.refcount(parent.page) == 1:
                heapq.heappush(
                    heap, (-parent.tier, parent.last_used,
                           parent.page, parent))
        return freed

    def evict_subtree_holding(self, page: int) -> int:
        """Drop the node caching `page` AND its whole subtree (children
        are unreachable without their parent on the lookup path).  Used
        under extreme pressure when the very page a slot must
        copy-on-write is only shared with the index — releasing the
        index's ref makes the page private and the copy unnecessary.
        Returns pages freed to the pool."""
        node = self._by_page.get(int(page))
        if node is None:
            return 0
        freed = 0
        stack = [node]
        order: List[_Node] = []
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):       # children before parents
            if self._drop_node(n):
                freed += 1
        return freed

    def clear(self) -> int:
        """Drop every cached prefix (pool deallocation / recovery: the
        pages' KV is gone, so no prefix may survive).  Returns pages
        freed to the pool."""
        freed = 0
        for node in self._by_page.values():
            if self._cache.drop_ref(node.page):
                freed += 1
        self._by_page.clear()
        self._root.clear()
        return freed
