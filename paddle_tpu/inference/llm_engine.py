"""Continuous-batching LLM serving engine over the paged KV cache.

The reference serves generation through a one-request-at-a-time predictor
loop (PaddleNLP over analysis_predictor.h:94).  Production TPU serving
(the Gemma-on-TPU study, arxiv 2605.25645) gets its throughput from
*continuous batching*: a fixed-width decode batch whose rows (slots) are
re-filled from a request queue the moment a sequence finishes, instead of
waiting for the whole batch to drain.

Engine anatomy:
  * `PagedKVCache` (models/generation.py) — page pools + page tables;
    each admitted request owns a decode slot and that slot's pages.
  * admission — pending requests enter free slots mid-flight; the prompt
    is prefilled through the dense flash path (bucketed to the next
    power-of-two length, so a handful of compiled programs cover all
    prompt lengths) and scattered into the slot's pages.
  * decode — ONE jitted step advances every active slot through the
    Pallas paged-attention kernel; empty slots point at the reserved
    scratch page and their logits are ignored.
  * eviction — on EOS or max_new_tokens the slot's pages return to the
    free pool and the slot re-enters admission.

Pages for prompt+max_new_tokens are reserved at admission (a request
either fits or stays queued) — reservation keeps the engine deadlock-free
without preemption; preemption/swap is the next step up, not built here.
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..models import generation

__all__ = ["LLMEngine", "serve_llm"]


class _Request:
    """One queued/in-flight generation request."""

    def __init__(self, prompt, max_new_tokens: int, eos_id: Optional[int]):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_id = eos_id
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self._event = threading.Event()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; returns the generated tokens
        (ending at eos_id when one was hit)."""
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def done(self) -> bool:
        return self._event.is_set()


class _SlotState:
    def __init__(self, req: _Request, last_tok: int, ctx: int):
        self.req = req
        self.last_tok = last_tok    # sampled, not yet in the cache
        self.ctx = ctx              # tokens currently cached


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class LLMEngine:
    """Continuous-batching generation engine (queue -> slots -> tokens).

    `num_slots` is the decode batch width (one compiled decode program);
    `num_pages` bounds resident KV memory — when smaller than worst-case
    num_slots occupancy, requests queue until pages free up.
    """

    def __init__(self, params, config, num_slots: int = 4,
                 page_size: int = 16, max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0):
        self.params = params
        self.config = config
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        self.max_seq_len = int(max_seq_len or config.max_position_embeddings)
        if self.max_seq_len > config.max_position_embeddings:
            # past the rope table jnp.take would silently clamp positions —
            # wrong tokens with no diagnostic
            raise ValueError(
                f"max_seq_len={self.max_seq_len} exceeds the model's "
                f"max_position_embeddings={config.max_position_embeddings}")
        pages_per_seq = -(-self.max_seq_len // page_size)
        if num_pages is None:
            num_pages = 1 + num_slots * pages_per_seq   # full provisioning
        self.cache = generation.PagedKVCache(
            config, num_pages=num_pages, page_size=page_size,
            max_slots=num_slots, pages_per_seq=pages_per_seq)
        self._pending: collections.deque = collections.deque()
        self._slots: dict[int, _SlotState] = {}
        self._key = jax.random.PRNGKey(seed)
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.stats = {"admitted": 0, "completed": 0, "decode_steps": 0,
                      "decode_tokens": 0}

        cfg = config

        # pools are DONATED: the caller always replaces cache.pools with the
        # result, so XLA updates the page pool in place instead of copying
        # the whole (L, P, ps, Hkv, D) cache every token (donation is a
        # no-op on CPU, where jax ignores it with a one-time warning)
        @functools.partial(jax.jit, donate_argnums=(4, 5))
        def _decode(params, tok, ctx, page_table, k_pool, v_pool):
            return generation.forward_paged_decode(
                params, tok, cfg, {"k": k_pool, "v": v_pool},
                page_table, ctx)

        self._decode = _decode

        @functools.partial(jax.jit, donate_argnums=(2, 3))
        def _prefill(params, ids, k_pool, v_pool, pt_row, true_len):
            # ids: (1, Sb) RIGHT-padded to the bucket; causal attention
            # keeps positions < true_len independent of the padding, and
            # padded positions scatter into the scratch page
            dense = generation.init_kv_cache(cfg, 1, ids.shape[1])
            logits, dense = generation.forward_with_cache(
                params, ids, cfg, dense, 0)
            pools = generation.scatter_prefill_into_pages(
                dense, {"k": k_pool, "v": v_pool}, pt_row, ids.shape[1],
                true_len=true_len[None])
            last = jnp.take_along_axis(
                logits, jnp.reshape(true_len - 1, (1, 1, 1)), axis=1)[:, 0]
            return last, pools["k"], pools["v"]

        self._prefill = _prefill

    # -- client surface -----------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None) -> _Request:
        req = _Request(prompt, max_new_tokens, eos_id)
        total = req.prompt.size + req.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens={total} exceeds engine "
                f"max_seq_len={self.max_seq_len}")
        if self.cache.pages_needed(total) > self.cache.num_pages - 1:
            raise ValueError(
                f"request needs {self.cache.pages_needed(total)} pages but "
                f"the pool only holds {self.cache.num_pages - 1}")
        with self._cv:
            if self._stop:
                raise RuntimeError("engine is stopped")
            self._pending.append(req)
            self._cv.notify()
        return req

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> List[List[int]]:
        """Synchronous convenience: submit all prompts and wait.  With the
        background loop running (start()/serve_llm) this only waits — the
        loop thread owns the cache; driving step() from a second thread
        would race slot/page allocation."""
        reqs = [self.submit(p, max_new_tokens, eos_id) for p in prompts]
        if self._thread is None:
            while not all(r.done() for r in reqs):
                if not self.step():
                    break  # no progress possible (errors already recorded)
            timeout = 0
        return [r.result(timeout=timeout) for r in reqs]

    # -- engine loop --------------------------------------------------------

    def has_work(self) -> bool:
        return bool(self._pending or self._slots)

    def step(self) -> bool:
        """One engine iteration: admit pending requests into free slots,
        advance every active slot one token, evict finished sequences.
        Returns True when any work was done."""
        admitted = self._admit()
        decoded = self._decode_step()
        return admitted or decoded

    def start(self):
        """Run the engine loop in a background thread (serving mode)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def shutdown(self, timeout: float = 10.0):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            # a mid-step thread owns the cache: releasing slots/pages under
            # it would hand the same pages to two sequences.  Re-join once
            # (a long decode step can outlive the first timeout), then
            # REFUSE to touch slot/page state while it is still alive.
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                err = RuntimeError("engine shut down (step thread wedged)")
                with self._cv:
                    for req in list(self._pending):
                        req.error = err
                        req._event.set()
                    self._pending.clear()
                raise RuntimeError(
                    f"engine step thread still running after "
                    f"{2 * timeout:.0f}s; queued requests were failed but "
                    "slots/pages were NOT released (the thread owns them) — "
                    "retry shutdown() once it finishes its step")
            self._thread = None
        # thread is gone (or never ran): fail anything still queued or in
        # flight so waiters unblock, and reclaim the slots
        err = RuntimeError("engine shut down")
        for req in list(self._pending):
            req.error = err
            req._event.set()
        self._pending.clear()
        for slot in list(self._slots):
            st = self._slots.pop(slot)
            st.req.error = err
            st.req._event.set()
            self.cache.release_slot(slot)

    def _loop(self):
        while True:
            with self._cv:
                while not self._stop and not self.has_work():
                    self._cv.wait()
                if self._stop:
                    return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — fail in-flight requests
                with self._cv:
                    for slot in list(self._slots):
                        st = self._slots.pop(slot)
                        st.req.error = e
                        st.req._event.set()
                        self.cache.release_slot(slot)
                    # _decode donates the pools too: recover them so the
                    # engine can admit new work after a failed step
                    self._recover_pools(e)

    def _recover_pools(self, cause: BaseException) -> bool:
        """If a failed donated dispatch consumed the k/v pools, re-zero
        them and fail every in-flight slot (their cached KV is gone).
        Returns True when recovery ran.  No-op while the buffers are
        alive (CPU, or a failure before dispatch)."""
        cache = self.cache
        try:
            dead = any(getattr(a, "is_deleted", lambda: False)()
                       for a in (cache.pools["k"], cache.pools["v"]))
        except Exception:  # noqa: BLE001 — treat unknown state as dead
            dead = True
        if not dead:
            return False
        err = RuntimeError(f"KV pools lost to a failed donated dispatch "
                           f"({cause!r:.120}); slot state was reset")
        for slot in list(self._slots):
            st = self._slots.pop(slot)
            st.req.error = err
            st.req._event.set()
            cache.release_slot(slot)
        cache.pools = generation.init_paged_kv_pools(
            self.config, cache.num_pages, cache.page_size)
        return True

    # -- internals ----------------------------------------------------------

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _sample(self, logits):
        return generation.sample_logits(
            logits, self._next_key(), self.temperature, self.top_k,
            self.top_p)

    def _admit(self) -> bool:
        cache = self.cache
        admitted = False
        while True:
            with self._cv:
                if not self._pending or cache.free_slot_count == 0:
                    break
                req = self._pending[0]
                total = req.prompt.size + req.max_new_tokens
                if cache.pages_needed(total) > cache.free_page_count:
                    break  # head-of-line waits for pages (no reordering)
                self._pending.popleft()
            slot = cache.acquire_slot()
            try:
                cache.ensure_capacity(slot, total)  # reserve at admission
                S = req.prompt.size
                # clamp the bucket to the rope table (non-power-of-2
                # max_position_embeddings would otherwise over-slice it)
                Sb = min(_bucket(S), self.config.max_position_embeddings)
                ids = np.zeros((1, Sb), np.int32)
                ids[0, :S] = req.prompt
                last, k_pool, v_pool = self._prefill(
                    self.params, jnp.asarray(ids), cache.pools["k"],
                    cache.pools["v"], cache.page_table[slot][None],
                    jnp.int32(S))
                cache.pools = {"k": k_pool, "v": v_pool}
                tok = int(np.asarray(self._sample(last))[0])
            except Exception as e:  # noqa: BLE001 — admission must not leak
                # the request left _pending but never reached _slots: without
                # cleanup the slot and its reserved pages leak forever and
                # result() blocks until timeout.  Release both, resolve the
                # handle with the error, and keep admitting — a per-request
                # failure (e.g. a prefill OOM at this bucket size) must not
                # wedge the engine.
                self._slots.pop(slot, None)
                if slot in cache._slot_pages:
                    cache.release_slot(slot)
                req.error = e
                req._event.set()
                # _prefill DONATES the pools: a dispatch that fails after
                # donation has already consumed them (TPU; CPU ignores
                # donation), and every later prefill/decode would die on
                # deleted buffers.  Re-zero the pools and fail the slots
                # whose KV lived in them.
                self._recover_pools(e)
                continue
            req.tokens.append(tok)
            self.stats["admitted"] += 1
            if (req.eos_id is not None and tok == req.eos_id) \
                    or req.max_new_tokens == 1:
                self._finish(slot, req)
            else:
                self._slots[slot] = _SlotState(req, tok, ctx=S)
            admitted = True
        return admitted

    def _decode_step(self) -> bool:
        if not self._slots:
            return False
        cache = self.cache
        B = cache.max_slots
        toks = np.zeros((B,), np.int32)
        ctx = np.zeros((B,), np.int32)   # empty slots hit the scratch page
        for slot, st in self._slots.items():
            # the incoming token lands at cache index st.ctx — make sure
            # that index's page exists (mid-decode page allocation)
            cache.ensure_capacity(slot, st.ctx + 1)
            toks[slot] = st.last_tok
            ctx[slot] = st.ctx
        logits, pools = self._decode(
            self.params, jnp.asarray(toks), jnp.asarray(ctx),
            cache.page_table, cache.pools["k"], cache.pools["v"])
        cache.pools = pools
        nxt = np.asarray(self._sample(logits))
        self.stats["decode_steps"] += 1
        self.stats["decode_tokens"] += len(self._slots)
        for slot in list(self._slots):
            st = self._slots[slot]
            st.ctx += 1
            tok = int(nxt[slot])
            st.req.tokens.append(tok)
            st.last_tok = tok
            if (st.req.eos_id is not None and tok == st.req.eos_id) \
                    or len(st.req.tokens) >= st.req.max_new_tokens:
                del self._slots[slot]
                self._finish(slot, st.req)
        return True

    def _finish(self, slot: int, req: _Request):
        self.cache.release_slot(slot)
        self.stats["completed"] += 1
        req._event.set()


def serve_llm(engine: LLMEngine, host: str = "127.0.0.1", port: int = 0,
              max_body_bytes: int = 8 * 1024 * 1024,
              request_timeout: float = 300.0):
    """HTTP JSON generation endpoint over a continuous-batching engine.

    POST / with {"prompt": [token ids], "max_new_tokens": N,
    "eos_id": optional} returns {"tokens": [...]}.  Concurrent requests
    share the engine's decode batch (continuous batching), so throughput
    scales with occupancy, not request count.  GET /stats returns engine
    counters.  Returns (server, thread); server.shutdown() stops the HTTP
    loop AND the engine."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    engine.start()

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, status: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path.rstrip("/") == "/stats":
                self._reply(200, dict(engine.stats,
                                      free_pages=engine.cache.free_page_count,
                                      free_slots=engine.cache.free_slot_count))
            else:
                self._reply(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", "0"))
                if n > max_body_bytes:
                    self._reply(413, {"error": "body too large"})
                    return
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = req["prompt"]
                    max_new = int(req.get("max_new_tokens", 16))
                    eos_id = req.get("eos_id")
                except (json.JSONDecodeError, KeyError, TypeError,
                        ValueError) as e:
                    self._reply(400, {"error": f"bad request body: {e!r}"})
                    return
                try:
                    handle = engine.submit(prompt, max_new, eos_id)
                except (ValueError, RuntimeError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                toks = handle.result(timeout=request_timeout)
                self._reply(200, {"tokens": toks})
            except Exception as e:  # noqa: BLE001 — server-side fault
                self._reply(500, {"error": repr(e)})

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    _orig_shutdown = srv.shutdown

    def _shutdown():
        _orig_shutdown()
        engine.shutdown()

    srv.shutdown = _shutdown
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t
