"""Continuous-batching LLM serving engine over the paged KV cache.

The reference serves generation through a one-request-at-a-time predictor
loop (PaddleNLP over analysis_predictor.h:94).  Production TPU serving
(the Gemma-on-TPU study, arxiv 2605.25645; Ragged Paged Attention, arxiv
2604.15464) gets its throughput from *continuous batching* and its memory
efficiency from *admitting on demand and preempting under pressure*
instead of reserving worst-case pages up front.

Engine anatomy:
  * `PagedKVCache` (models/generation.py) — page pools + page tables;
    each admitted request owns a decode slot and that slot's pages.
  * admission — pending requests enter free slots mid-flight with NO
    dispatch of their own: a fresh request just starts its prompt as a
    ragged prefill that the next unified step advances chunk by chunk.
  * the unified ragged step — each step builds ONE ragged batch: every
    decoding slot contributes a 1-token span and prefilling slots
    contribute bounded chunks admitted under a per-step token budget
    (`prefill_chunk_tokens`), all through ONE dispatch of the Pallas
    ragged-attention kernel (kernels/pallas_ragged_attention.py) over
    the paged pools.  The batch arrays are FIXED-SHAPE, so steady state
    is O(1) compiled executables — there is no prefill bucket menu and
    no per-prompt-length recompile class at all.  Prefill chunks
    interleave with decode, so a long prompt never stalls other
    requests' inter-token latency for more than one chunk.
  * page allocation is on demand per span (chunk or decode token) and
    may FAIL under pressure.
  * preemption — when mid-step allocation fails, a victim is picked
    (`victim_policy`: "latest" admitted, or "fewest_tokens" generated),
    its pages are released, and the request re-enters the HEAD of the
    pending deque carrying either a host copy of its KV pages
    (`preempt_mode="swap"`: gather at preempt, scatter back on resume)
    or nothing (`preempt_mode="recompute"`: the whole context — prompt
    plus generated-so-far — is simply appended to later ragged batches
    as chunked spans; resume IS a ragged prefill).  Mid-prefill victims
    are preemptible too: swap carries the chunks already cached,
    recompute starts the prompt over.  The LAST runnable sequence is
    never preempted — and a single request's worst case is validated
    against the pool at submit() — so forward progress is
    deadlock-free.
  * eviction — on EOS / max_new_tokens / cancel() / deadline expiry the
    slot's pages return to the free pool and the slot re-enters admission.

Request lifecycle: `submit()` returns a handle with `result()`, `done()`
and `cancel()`; per-request deadlines are enforced at every step()
boundary (queued or mid-decode -> `DeadlineExceeded`); the pending queue
is bounded (`max_pending`) and overflow raises a typed `QueueFull`
(HTTP 503 + Retry-After in serve_llm).  `serve_llm` maps a `result()`
timeout to HTTP 504 AND cancels the request so its slot/pages free
immediately instead of starving the batch until max_new_tokens.

Every failure path is exercised by the fault-injection harness in
`paddle_tpu.inference.faults`: the engine calls `faults.fire(point, ...)`
at named injection points (prefill / decode / page_alloc / sample /
swap_out / swap_in) and the harness's invariant checker proves no pages,
slots or handles leak under any schedule.

Telemetry (paddle_tpu.obs): every lifecycle counter lives in a metrics
Registry (`engine.metrics`) — `stats_snapshot()` (the /stats JSON) and
`GET /metrics` (Prometheus text) read the SAME storage, so the two
surfaces cannot drift.  Per-request latency metrics are derived from
lifecycle timestamps: queue wait (submit -> admission), TTFT (submit ->
first token), inter-token gaps, and tokens/sec.  The step loop is span-
instrumented (admit / prefill / decode_step / sample / preempt /
swap_out / swap_in) against `engine.tracer` — a no-op single branch
until the tracer is enabled, with `block_until_ready` fencing on the
dispatch results so spans time the compute, not the enqueue.
"""

from __future__ import annotations

import collections
import collections.abc
import functools
import re
import threading
import time
import warnings
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import kvstore as _kvstore
from . import prefix as _prefix
from . import qos as _qos
from .. import kernels
from ..models import generation
from ..obs import metrics as obs_metrics
from ..obs import reqtrace as obs_reqtrace
from ..obs import slo as obs_slo
from ..obs import stepprof as obs_stepprof
from ..obs import trace as obs_trace
from ..obs import watchdog as obs_watchdog

__all__ = ["LLMEngine", "serve_llm", "QueueFull", "RequestCancelled",
           "DeadlineExceeded", "EngineStopped", "PrefillHandoff"]


class EngineStopped(RuntimeError):
    """submit() refused: the engine is shut down OR its step thread died.
    Typed and immediate — enqueueing into a dead loop would hand back a
    handle no thread will ever resolve, so result() would hang forever.
    The fleet Router treats this as replica death (eject + place
    elsewhere); serve_fleet maps it to HTTP 503."""


class QueueFull(RuntimeError):
    """submit() refused: the bounded pending queue is at capacity.
    serve_llm maps this to HTTP 503 with a Retry-After header."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = float(retry_after)


class RequestCancelled(RuntimeError):
    """The request was cancelled before it finished."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before it finished."""


class PrefillHandoff(RuntimeError):
    """NOT a failure: a prefill-class replica resolved this request at
    `prefill_done` with ZERO tokens emitted and its prompt's KV pages
    staged host-side for transfer (`.handoff`, a `kvstore.KVHandoff`).
    The fleet Router brokers the payload to a decode-class replica and
    re-places the request there — zero tokens means the retry rule
    (re-place iff nothing was emitted) always applies, so a prefill
    replica dying mid-transfer is safely retryable with the request's
    remaining deadline.  A direct caller seeing this from `result()`
    submitted to a prefill-class engine without a router; submit with
    `handoff=False` to make such an engine decode locally instead."""

    def __init__(self, handoff: "_kvstore.KVHandoff"):
        super().__init__(
            "prefill complete; KV staged for decode-replica handoff")
        self.handoff = handoff


class _ResumeState:
    """What a preempted request needs to re-enter a slot: cached-token
    count, the sampled-but-not-yet-cached token (None mid-prefill), how
    many pages it held, the not-yet-cached span still to prefill
    (`pending`, None once prefill finished), whether finishing that
    prefill should sample a first token, and (swap mode only) host copies
    of the cached pages' KV.  In recompute mode ctx is 0 and `pending`
    holds the WHOLE context — resume is just a ragged prefill."""

    __slots__ = ("ctx", "last_tok", "n_pages", "pending",
                 "sample_on_finish", "host_k", "host_v")

    def __init__(self, ctx: int, last_tok: Optional[int], n_pages: int,
                 pending=None, sample_on_finish: bool = False,
                 host_k=None, host_v=None):
        self.ctx = ctx
        self.last_tok = last_tok
        self.n_pages = n_pages
        self.pending = pending
        self.sample_on_finish = sample_on_finish
        self.host_k = host_k
        self.host_v = host_v


class _Request:
    """One queued/in-flight generation request.  `req_id` keys the
    request's timeline in the obs request registry (generated when the
    caller brings none); `hop` is the fleet-level placement count this
    engine-level attempt represents (0 = first placement — the Router
    stamps retries with their hop index so a retried request's timeline
    shows which events belong to which replica attempt)."""

    def __init__(self, prompt, max_new_tokens: int, eos_id: Optional[int],
                 deadline: Optional[float] = None,
                 req_id: Optional[str] = None, hop: int = 0,
                 tenant: str = _qos.DEFAULT_TENANT, priority: int = 1):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_id = eos_id
        self.req_id = req_id or obs_reqtrace.new_request_id()
        self.hop = int(hop)
        # QoS labels, resolved by submit() through the engine's policy:
        # the tenant keys WFQ lanes / per-tenant counters, the EFFECTIVE
        # priority tier (lower = more important) orders admission and
        # the preemption/eviction ladder
        self.tenant = str(tenant)
        self.priority = int(priority)
        # may a prefill-class engine resolve this request at prefill_done
        # with a KV handoff instead of decoding?  Stamped by submit()
        self.allow_handoff = False
        self.deadline = (None if deadline is None
                         else time.monotonic() + float(deadline))
        # lifecycle timestamps (monotonic): the per-request latency
        # metrics — queue wait, TTFT, inter-token — derive from these
        self.t_submit = time.monotonic()
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.cancelled = False
        self.resolutions = 0        # invariant: exactly 1 once done()
        self._resume: Optional[_ResumeState] = None
        self._engine: Optional["LLMEngine"] = None
        self._event = threading.Event()
        # fired once, on the FIRST resolution (routers hook completion
        # here instead of polling done()); exceptions are swallowed — a
        # broken observer must not wedge the step thread
        self._callbacks: List = []

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; returns the generated tokens
        (ending at eos_id when one was hit)."""
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        """Cancel the request: a queued one resolves immediately with
        RequestCancelled; an in-flight one is evicted (pages released) at
        the next step() boundary.  No-op once done."""
        eng = self._engine
        if eng is None:
            self.cancelled = True
            return
        with eng._cv:
            if self.done():
                return
            self.cancelled = True
            try:
                eng._pending.remove(self)
            except ValueError:
                eng._cv.notify_all()   # in flight: wake the loop to evict
                return
            eng.stats["cancelled"] += 1
            self._resolve(RequestCancelled("request cancelled"))

    def _resolve(self, error: Optional[BaseException] = None) -> None:
        # counts EVERY call, even redundant ones, so the invariant checker
        # can prove each handle resolved exactly once
        self.resolutions += 1
        if self._event.is_set():
            return
        self.error = error
        self._event.set()
        for cb in list(self._callbacks):
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — observer bug stays local
                pass


class _SlotState:
    """One occupied decode slot.  A slot is PREFILLING while `pending`
    still holds uncached tokens (ctx < pending.size) and DECODING once
    pending is None — then `last_tok` is the sampled-but-not-yet-cached
    token the next 1-token span will write at position ctx."""

    def __init__(self, req: _Request, admit_seq: int, ctx: int = 0,
                 last_tok: Optional[int] = None, pending=None,
                 sample_on_finish: bool = True, spec_k: int = 0):
        self.req = req
        self.admit_seq = admit_seq  # admission order (victim policy)
        self.ctx = ctx              # tokens currently cached
        self.last_tok = last_tok    # sampled, not yet in the cache
        self.pending = pending      # np.int32 tokens still to prefill
        # sample a first token when prefill completes?  True for fresh
        # prompts; False for recompute-resume (its next token was already
        # sampled before the preemption)
        self.sample_on_finish = sample_on_finish
        # adaptive speculative draft length for THIS slot, within
        # [1, engine.spec_k]; reset to the engine default on (re)admission
        # — a preempted slot resumes with speculation state reset
        self.spec_k = spec_k

    @property
    def prefilling(self) -> bool:
        return self.pending is not None and self.ctx < self.pending.size


class _StatsDict(collections.abc.MutableMapping):
    """The engine's legacy counter dict, backed by registry Counters.

    Call sites keep writing `stats["completed"] += 1`; each key is ONE
    `<prefix>_<key>_total` Counter in the metrics registry, so /stats
    JSON and /metrics Prometheus text read identical storage and cannot
    drift.  (Keys already ending in `_total` keep their name:
    "steps_total" -> `llm_steps_total`.)  The Router reuses this with
    prefix="fleet" for its own counters."""

    _HELP = {
        "accepted": "requests accepted by submit() (queued or better)",
        "admitted": "fresh admissions prefillled into a slot",
        "completed": "requests finished with tokens",
        "decode_steps": "ragged dispatches advancing >=1 decoding slot",
        "decode_tokens": "tokens produced by decode spans",
        "prefill_chunks": "prefill chunk spans dispatched",
        "prefill_tokens": "prompt/context tokens prefilled via chunks",
        "ragged_batch_tokens": "total valid tokens across ragged "
                               "dispatches (decode + prefill + verify "
                               "spans)",
        "verify_tokens": "rows dispatched in speculative verify spans "
                         "(last token + drafts)",
        "prefix_hits": "admissions that spliced a cached prefix",
        "prefix_misses": "admissions that found no cached prefix",
        "prefix_spliced_pages": "KV pages spliced from the prefix index "
                                "instead of re-prefilled",
        "prefix_cow_copies": "shared pages copied privately before a "
                             "slot appended into them (copy-on-write)",
        "prefix_evictions": "cached prefix pages evicted under page "
                            "pressure (LRU)",
        "spec_steps": "speculative verify spans dispatched",
        "spec_drafted": "draft tokens proposed into verify spans",
        "spec_accepted": "draft tokens accepted by the verify pass",
        "spec_rejected": "draft tokens rejected by the verify pass",
        "spec_bonus": "verify-span bonus rows sampled (correction at the "
                      "first rejection, or the free token after full "
                      "acceptance; one per verify span)",
        "spec_emitted": "tokens emitted by verify spans (accepted drafts "
                        "+ the bonus/correction, minus any cut by "
                        "eos/max_new_tokens)",
        "emitted_tokens": "tokens appended to request streams (decode + "
                          "verify emissions; the per-tenant twins must "
                          "sum to this)",
        "preemptions": "victims evicted under page pressure",
        "swapped_in": "preempted requests resumed via host-KV scatter",
        "swap_out_pages": "KV pages gathered to host RAM at preemption",
        "swap_in_pages": "KV pages scattered back from host RAM on "
                         "resume",
        "resumed": "preempted requests re-admitted (either mode)",
        "cancelled": "requests resolved by cancellation",
        "timed_out": "requests resolved by deadline expiry",
        "failed": "requests resolved with an engine/dispatch error",
        "steps_total": "engine step() iterations",
        "handoffs": "requests resolved at prefill_done with a KV "
                    "handoff (disaggregated prefill->decode transfer)",
        "kv_transfer_pages": "KV pages moved over the prefill->decode "
                             "transfer path (export + import)",
        "kv_transfer_bytes": "payload bytes moved over the "
                             "prefill->decode transfer path",
        "kv_demoted_pages": "evicted prefix pages demoted to the host "
                            "tier instead of discarded",
        "kv_promoted_pages": "host-tier pages promoted back to the "
                             "device prefix index at admission",
        "prefix_tier_hits": "admissions whose splice extended past the "
                            "device tier via host-tier promotion",
    }

    def __init__(self, registry: obs_metrics.Registry,
                 keys: Sequence[str], prefix: str = "llm",
                 help: Optional[dict] = None):
        self._registry = registry
        self._prefix = prefix
        self._help = dict(self._HELP) if help is None else dict(help)
        self._counters = {}
        for k in keys:
            self._counters[k] = self._make(k)

    def _make(self, key: str) -> obs_metrics.Counter:
        name = (f"{self._prefix}_{key}" if key.endswith("_total")
                else f"{self._prefix}_{key}_total")
        return self._registry.counter(name, self._help.get(key, ""))

    def __getitem__(self, key: str) -> int:
        return int(self._counters[key].value)

    def __setitem__(self, key: str, value) -> None:
        if key not in self._counters:
            self._counters[key] = self._make(key)
        self._counters[key].set(value)

    def inc(self, key: str, n: int = 1) -> None:
        """Atomic increment (Counter.inc holds the metric's lock).
        `stats[k] += 1` is a separate read then absolute write — fine
        under the engine's _cv, but the Router bumps counters from HTTP
        handler, engine step, and health-tick threads concurrently,
        where the read-modify-write loses counts."""
        if key not in self._counters:
            self._counters[key] = self._make(key)
        self._counters[key].inc(n)

    def __delitem__(self, key: str) -> None:
        raise TypeError("engine stats counters cannot be removed")

    def __iter__(self):
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)


class LLMEngine:
    """Continuous-batching generation engine (queue -> slots -> tokens).

    `num_slots` is the decode batch width (one compiled decode program);
    `num_pages` bounds resident KV memory — when smaller than worst-case
    num_slots occupancy the engine admits on demand and PREEMPTS under
    pressure (see module docstring), so a pool sized for the *expected*
    footprint still serves the worst case correctly, just slower.

    preempt_mode: "swap" (KV pages copied to host at preempt, scattered
    back on resume) or "recompute" (the whole context re-enters later
    ragged batches as chunked prefill spans).  victim_policy: "latest"
    (latest-admitted) or "fewest_tokens" (least work lost).  max_pending
    bounds the queue (QueueFull beyond).
    faults: an optional paddle_tpu.inference.faults.FaultInjector.
    tracer: a paddle_tpu.obs.Tracer (default: the process-wide tracer,
    disabled until enabled — instrumentation is then a no-op branch).
    metrics: a paddle_tpu.obs.Registry (default: a fresh per-engine
    registry; serve_llm's GET /metrics renders it).
    name: the replica name stamped on request-timeline events (the
    fleet Router overrides it with the replica id).
    reqtrace: a paddle_tpu.obs.RequestRegistry (default: the process-
    wide registry, shared with the Router so a retried request's hops
    land in ONE timeline; GET /debug/request/<id> reads it).
    flight: a paddle_tpu.obs.FlightRecorder — armed here, the engine
    dumps a black-box frame when its step thread dies.
    slo_objectives / slo_window_s: latency objectives for the per-
    engine SLO engine (default obs.slo.DEFAULT_OBJECTIVES over a 60s
    window); its gauges/burn rates render on /metrics and /stats.
    stepprof: a paddle_tpu.obs.StepProfiler (default: a fresh armed
    one) — per-step phase attribution (schedule / build_batch /
    dispatch / sample / verify / commit / swap + other); its rolling
    shares ride /stats ("step_phases") and per-phase gauges render on
    /metrics.  Disable with StepProfiler(enabled=False).
    watchdog: a paddle_tpu.obs.Watchdog (default: a fresh armed one) —
    rolling-baseline spike detection over step time and inter-token
    latency; on a sustained spike it names the guilty phase(s) and
    drops a `step_anomaly` flight dump through `flight`.

    prefill_chunk_tokens: the per-step TOKEN BUDGET for prefill chunks
    riding the unified ragged batch alongside decode spans.  Smaller =
    tighter inter-token latency for in-flight requests under concurrent
    prefill; larger = faster time-to-first-token for new prompts.  The
    ragged batch is sized at construction (num_slots decode rows plus
    this budget, block_q-aligned), so the step stays ONE compiled
    executable regardless of prompt lengths — there is no bucket menu.
    block_q: the kernel's query row-block size; every span occupies
    whole blocks (a decode span pads one block).

    spec_k: speculative decoding — the MAX draft tokens per decoding
    slot per step (0 disables it; the default).  Each step the drafter
    proposes up to k tokens per decoding slot, and the slot's span
    becomes a (k+1)-row VERIFY span ([last_token] + drafts) through the
    SAME ragged dispatch as prefill chunks — verifying k drafts costs
    one span in one dispatch, not k steps.  The accept/reject pass is
    greedy-exact at temperature 0 (accept the longest argmax-agreeing
    prefix) and rejection sampling otherwise (the output DISTRIBUTION
    matches non-speculative sampling exactly).  Rejected drafts roll
    back by per-slot ctx truncation — pages are append-only, so the KV
    they wrote is logically retired and overwritten in place.  k is
    ADAPTIVE per slot within [1, spec_k] (grows on full acceptance,
    shrinks on low), and the batch geometry is sized ONCE for spec_k,
    so varying k never changes the compiled signature.
    drafter: a generation.Drafter (default: NGramDrafter prompt-lookup —
    no second model); ignored when spec_k == 0.

    prefix_cache: cross-user prefix reuse (default ON).  The page pool is
    refcounted with copy-on-write; a radix prefix index (inference/
    prefix.py) remembers where every finished prefill's KV lives.
    Admission looks up the longest cached prefix of a new prompt and
    SPLICES its pages into the slot (page-table bookkeeping, zero
    dispatch), so chunked prefill shrinks to the unshared suffix; a slot
    that must append into a partially-filled shared page first copies it
    privately through ONE compiled page-copy executable.  Cached-but-
    unreferenced prefixes are LRU-evicted only under page pressure,
    before any live sequence is preempted; pool recovery invalidates the
    whole index (a prefix must not outlive its KV).  False disables the
    index (no lookups, no retention — refcounts stay all-1).
    """

    def __init__(self, params, config, num_slots: int = 4,
                 page_size: int = 16, max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 max_pending: Optional[int] = None,
                 preempt_mode: str = "swap",
                 victim_policy: str = "latest",
                 faults=None,
                 prefill_chunk_tokens: int = 64,
                 block_q: int = 8,
                 spec_k: int = 0,
                 drafter=None,
                 prefix_cache: bool = True,
                 tracer: Optional[obs_trace.Tracer] = None,
                 metrics: Optional[obs_metrics.Registry] = None,
                 name: Optional[str] = None,
                 reqtrace: Optional[obs_reqtrace.RequestRegistry] = None,
                 flight=None,
                 slo_objectives=None,
                 slo_window_s: float = 60.0,
                 stepprof: Optional[obs_stepprof.StepProfiler] = None,
                 watchdog: Optional[obs_watchdog.Watchdog] = None,
                 fused_decode: bool = True,
                 role: str = "mixed",
                 kvstore=None,
                 tenants=None):
        self.params = params
        self.config = config
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        self.max_seq_len = int(max_seq_len or config.max_position_embeddings)
        if self.max_seq_len > config.max_position_embeddings:
            # past the rope table jnp.take would silently clamp positions —
            # wrong tokens with no diagnostic
            raise ValueError(
                f"max_seq_len={self.max_seq_len} exceeds the model's "
                f"max_position_embeddings={config.max_position_embeddings}")
        if preempt_mode not in ("swap", "recompute"):
            raise ValueError(f"unknown preempt_mode {preempt_mode!r}")
        if victim_policy not in ("latest", "fewest_tokens"):
            raise ValueError(f"unknown victim_policy {victim_policy!r}")
        self.preempt_mode = preempt_mode
        self.victim_policy = victim_policy
        # disaggregated serving: a "prefill"-class engine resolves
        # handoff-eligible requests at prefill_done with their KV staged
        # for a decode-class replica; "decode" marks the engine a
        # continuation target for the Router's role-aware placement (it
        # still runs prefill for the unshared suffix of a handoff, and
        # everything when no handoff arrived); "mixed" is the classic
        # single-engine behaviour.  A Router may FLIP the role between
        # steps under sustained load imbalance — nothing here is baked
        # into a compiled program, so flipping costs zero recompiles.
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown role {role!r}")
        self.role = role
        self.max_pending = None if max_pending is None else int(max_pending)
        # multi-tenant QoS: the tenant table + resolution rules
        # (inference/qos.py).  tenants=None builds the implicit
        # single-"default"-tenant policy — FIFO-equivalent, zero cost;
        # an explicit table turns admission into weighted-fair queueing
        # with per-tenant caps and makes the preemption/eviction ladder
        # priority-aware.  Tenancy is entirely host-side scheduling: no
        # compiled program ever sees a tenant label.
        self.qos = _qos.QoSPolicy.build(tenants)
        self.faults = faults
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        if self.prefill_chunk_tokens < 1:
            raise ValueError("prefill_chunk_tokens must be >= 1")
        self.block_q = int(block_q)
        if self.block_q < 1:
            raise ValueError("block_q must be >= 1")
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        self._drafter = None
        if self.spec_k > 0:
            self._drafter = (drafter if drafter is not None
                             else generation.NGramDrafter())
        # the ragged batch's fixed geometry: every decoding slot takes
        # ceil((1 + spec_k) / block_q) row blocks (1 decode token plus up
        # to spec_k drafts to verify), prefill chunks take
        # ceil(budget / block_q) more — sized ONCE here for the maximum
        # k, so the unified step is ONE compiled executable regardless
        # of how many drafts each slot carries on a given step
        self._num_blocks = \
            num_slots * -(-(1 + self.spec_k) // self.block_q) \
            + -(-self.prefill_chunk_tokens // self.block_q)
        self._num_spans = num_slots + 1      # + the padding span
        # fixed logits-gather width: every slot's span may ask for up to
        # 1 + spec_k out rows (a verify span needs ALL its rows); with
        # speculation off this is exactly num_spans — the classic
        # one-logits-row-per-span signature, unchanged
        self._num_out = (self._num_spans if self.spec_k == 0
                         else num_slots * (1 + self.spec_k) + 1)
        pages_per_seq = -(-self.max_seq_len // page_size)
        if num_pages is None:
            num_pages = 1 + num_slots * pages_per_seq   # full provisioning
        self.cache = generation.PagedKVCache(
            config, num_pages=num_pages, page_size=page_size,
            max_slots=num_slots, pages_per_seq=pages_per_seq)
        # cross-user prefix reuse: the radix index holds refcounts on
        # pages whose KV outlives the slot that computed it
        self.prefix_index = (_prefix.PrefixIndex(self.cache)
                             if prefix_cache else None)
        self._prefix_evicted_seen = 0   # evictions already counted
        # host-tier prefix store (kvstore.TieredPrefixStore): demotions
        # flow out on LRU eviction, promotions flow in at admission
        self.kvstore = None
        # KV handoffs queued for import (router -> step thread): the
        # import's pool mutation runs ONLY on the step thread
        self._kv_imports: collections.deque = collections.deque()
        if kvstore is not None:
            self.attach_kvstore(kvstore)
        # the pending queue: weighted-fair per-tenant lanes behind the
        # same deque API the checkers/cancellation path consume.  With
        # the implicit single-tenant policy its behaviour is exactly the
        # FIFO deque it replaced.
        self._pending: _qos.WFQQueue = _qos.WFQQueue(self.qos)
        # threadlint: owned=_loop — the slot table is step-thread-owned,
        # mutated lock-free on the hot path; shutdown() touches it only
        # AFTER joining the step thread (line-acknowledged there)
        self._slots: dict[int, _SlotState] = {}
        self._admit_seq = 0
        self._key = jax.random.PRNGKey(seed)
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._t_start = time.monotonic()
        self.tracer = tracer if tracer is not None else obs_trace.get_tracer()
        self.metrics = metrics if metrics is not None \
            else obs_metrics.Registry()
        self.replica_name = "engine" if name is None else str(name)
        self.reqtrace = reqtrace if reqtrace is not None \
            else obs_reqtrace.get_request_registry()
        self.flight = None
        if self.metrics.get("llm_accepted_total") is not None:
            # a shared registry would silently merge both engines'
            # counters and rebind the state gauges to the last engine —
            # corrupted numbers, no error.  Fail fast instead: one
            # registry per engine; a router aggregates per-replica
            # renders, it does not pool storage.
            raise ValueError(
                "metrics registry already serves another LLMEngine; "
                "give each engine its own Registry")
        # threadlint: atomic — _StatsDict routes every mutation through
        # the backing registry Counter's own lock (the PR 9 fix), so
        # step-thread bumps vs submit-path bumps under _cv never race
        self.stats = _StatsDict(self.metrics, (
            "accepted", "admitted", "completed", "decode_steps",
            "decode_tokens", "fused_decode_steps",
            "prefill_chunks", "prefill_tokens",
            "ragged_batch_tokens", "verify_tokens", "spec_steps",
            "spec_drafted", "spec_accepted", "spec_rejected", "spec_bonus",
            "spec_emitted", "emitted_tokens",
            "preemptions", "swapped_in", "resumed",
            "swap_out_pages", "swap_in_pages",
            "prefix_hits", "prefix_misses", "prefix_spliced_pages",
            "prefix_cow_copies", "prefix_evictions",
            "cancelled", "timed_out", "failed", "steps_total",
            "handoffs", "kv_transfer_pages", "kv_transfer_bytes",
            "kv_demoted_pages", "kv_promoted_pages", "prefix_tier_hits"))
        reg = self.metrics
        self._h_queue_wait = reg.histogram(
            "llm_queue_wait_seconds", "submit() -> slot admission")
        self._h_ttft = reg.histogram(
            "llm_ttft_seconds", "submit() -> first generated token")
        self._h_itl = reg.histogram(
            "llm_inter_token_seconds",
            "gap between consecutive tokens of one request (includes "
            "preemption/requeue time: the latency the CLIENT sees)")
        self._h_tps = reg.histogram(
            "llm_request_tokens_per_sec",
            "per completed request: tokens / (finish - admission)",
            buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000,
                     5000, 10000))
        self._h_accept = reg.histogram(
            "llm_spec_accept_ratio",
            "per verify span: accepted drafts / drafts proposed (the "
            "per-slot acceptance signal; adaptive k feeds on this)",
            buckets=(0.0, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8,
                     0.9, 1.0))
        # replica-level acceptance: cumulative accepted/drafted, 1.0
        # (neutral) before any drafting — the fleet Router folds this
        # into its placement score so low-acceptance replicas (which
        # burn more verify rows per emitted token) lose placement
        reg.gauge(
            "llm_spec_acceptance_rate",
            "cumulative speculative acceptance: accepted/drafted "
            "(1.0 until the first draft)").set_function(
            lambda: (self.stats["spec_accepted"]
                     / self.stats["spec_drafted"])
            if self.stats["spec_drafted"] else 1.0)
        # gauges read engine state lazily at render/snapshot time (the
        # slot/page structures are owned lock-free by the step thread, so
        # a gauge can be one step fresher than the counters next to it)
        reg.gauge("llm_queue_depth", "pending requests").set_function(
            lambda: len(self._pending))
        reg.gauge("llm_slots_in_flight", "occupied decode slots"
                  ).set_function(lambda: len(self._slots))
        reg.gauge("llm_free_pages", "KV pages in the free pool"
                  ).set_function(lambda: self.cache.free_page_count)
        reg.gauge("llm_free_slots", "free decode slots").set_function(
            lambda: self.cache.free_slot_count)
        reg.gauge("llm_uptime_seconds", "seconds since engine construction"
                  ).set_function(lambda: time.monotonic() - self._t_start)
        # per-engine SLO engine over the same lifecycle latencies the
        # histograms record: rolling-window percentile gauges, burn
        # rates, and violation counters land in THIS registry (so
        # /metrics shows objective health next to the raw histograms)
        # and stats_snapshot() carries report() for /stats
        self.slo = obs_slo.SLOEngine(
            objectives=(slo_objectives if slo_objectives is not None
                        else obs_slo.DEFAULT_OBJECTIVES),
            window_s=slo_window_s).register(reg)
        # per-tenant accounting: counters, queue-depth gauge, and an SLO
        # engine (objectives cloned under tenant_<t>_* names so burn
        # rates per tenant render on /metrics next to the engine-wide
        # ones).  Explicit tenant tables materialize eagerly so their
        # gauges exist before traffic; auto-vivified labels materialize
        # on first submit.
        self._slo_window_s = float(slo_window_s)
        self._tenant_stats: dict = {}
        self._tenant_slo: dict = {}
        for _t in self.qos.tenants():
            self._tenant_state(_t)
        # per-step phase attribution + the anomaly watchdog feeding on
        # it: both default-armed (bench extra.obs_overhead pins the
        # whole layer, profiler + pool telemetry + watchdog, < 2% of
        # decode ITL)
        self.stepprof = stepprof if stepprof is not None \
            else obs_stepprof.StepProfiler()
        self.stepprof.register_gauges(reg)
        self.watchdog = watchdog if watchdog is not None \
            else obs_watchdog.Watchdog()
        self.watchdog.bind(tracer=self.tracer, registry=reg)
        # the dispatch phase's shape class: the fixed ragged-batch
        # geometry (query rows x spans x out rows) — the key a
        # per-generation kernel autotuner caches tuned winners under
        self._shape_class = (f"T{self._num_blocks * self.block_q}"
                             f"xS{self._num_spans}xO{self._num_out}")
        # the fused single-dispatch decode step profiles under its own
        # key: the same batch geometry but a different executable (the
        # sampling epilogue rides inside), so an autotuner/stepprof
        # track must never mix the two dispatch shapes
        self._shape_class_fused = self._shape_class + "+fused"
        # KV-pool & scheduler memory telemetry, sampled every step:
        # watermarks and fragmentation are step-thread-owned floats the
        # gauges read lazily (same freshness contract as the pool
        # gauges above)
        self._pool_free_low_wm = self.cache.free_page_count
        self._pool_used_high_wm = 0
        self._frag_max_run = self.cache.free_page_count
        self._frag_ratio = 1.0
        self._frag_stale = 0        # traced counter-track refresh cadence
        self._last_batch_tokens = 0
        reg.gauge("llm_pool_pages_total",
                  "allocatable KV pages (page 0 is reserved scratch)"
                  ).set(self.cache.num_pages - 1)
        reg.gauge("llm_pool_used_pages", "KV pages held by slots"
                  ).set_function(
            lambda: self.cache.num_pages - 1 - self.cache.free_page_count)
        reg.gauge("llm_pool_free_low_watermark",
                  "fewest free pages ever observed at a step boundary"
                  ).set_function(lambda: self._pool_free_low_wm)
        reg.gauge("llm_pool_used_high_watermark",
                  "most pages ever held at a step boundary"
                  ).set_function(lambda: self._pool_used_high_wm)
        reg.gauge("llm_pool_frag_max_run",
                  "longest contiguous run of free page ids (computed "
                  "at scrape time)").set_function(
            lambda: self._compute_frag())
        def _frag_ratio_read():
            self._compute_frag()
            return self._frag_ratio

        reg.gauge("llm_pool_frag_ratio",
                  "max contiguous free run / free total (1.0 = "
                  "unfragmented; pages are random-access, so this "
                  "tracks allocator churn, not correctness)"
                  ).set_function(_frag_ratio_read)
        reg.gauge("llm_batch_tokens",
                  "valid tokens in the most recent ragged batch "
                  "(decode + prefill + verify rows)").set_function(
            lambda: self._last_batch_tokens)
        reg.gauge("llm_slot_pages_max",
                  "largest per-slot page count right now"
                  ).set_function(lambda: max(
                      (len(p) for p in
                       list(self.cache._slot_pages.values())), default=0))
        reg.gauge("llm_prefix_cached_pages",
                  "KV pages the prefix index holds a reference on "
                  "(reclaimable under pressure, shareable on a hit)"
                  ).set_function(
            lambda: (0 if self.prefix_index is None
                     else self.prefix_index.cached_pages))
        if flight is not None:
            flight.attach_engine(self)

        cfg = config

        # THE unified step: one dispatch per engine iteration, decode
        # spans and prefill chunks in the same ragged batch.  Pools are
        # DONATED: the caller always replaces cache.pools with the
        # result, so XLA updates the page pool in place instead of
        # copying the whole (L, P, ps, Hkv, D) cache every token
        # (donation is a no-op on CPU, where jax ignores it with a
        # one-time warning).  All batch arrays are fixed-shape, so this
        # compiles exactly once — no bucket menu, no recompiles.
        @functools.partial(jax.jit, donate_argnums=(11, 12))
        def _ragged(params, tok, row_page, row_off, row_pos, block_seq,
                    block_qpos, span_len, ctx_len, span_pt, out_rows,
                    k_pool, v_pool):
            logits, pools = generation.forward_ragged(
                params, tok, cfg, {"k": k_pool, "v": v_pool}, row_page,
                row_off, row_pos, block_seq, block_qpos, span_len,
                ctx_len, span_pt, out_rows)
            return logits, pools["k"], pools["v"]

        self._ragged = _ragged

        # THE fused variant: same trunk, but the lm_head matmul +
        # temperature/top-k/top-p filtering + categorical sampling run
        # INSIDE the dispatch (kernels.fused_decode_step), so a plain
        # decode step pulls (num_out,) int32 token ids instead of the
        # (num_out, V) f32 logits block.  The PRNG key is a traced ARG
        # (the knobs are engine-lifetime statics), so this too compiles
        # exactly once; pools donated the same way.
        t_, tk_, tp_ = self.temperature, self.top_k, self.top_p

        @functools.partial(jax.jit, donate_argnums=(12, 13))
        def _ragged_fused(params, tok, row_page, row_off, row_pos,
                          block_seq, block_qpos, span_len, ctx_len,
                          span_pt, out_rows, key, k_pool, v_pool):
            toks, pools = generation.forward_ragged_sample(
                params, tok, cfg, {"k": k_pool, "v": v_pool}, row_page,
                row_off, row_pos, block_seq, block_qpos, span_len,
                ctx_len, span_pt, out_rows, key, temperature=t_,
                top_k=tk_, top_p=tp_)
            return toks, pools["k"], pools["v"]

        self._ragged_fused = _ragged_fused
        # verify-or-rollback, never silent: the fused epilogue must
        # prove itself token-exact (greedy) / chi-square-clean (sampled)
        # against the unfused reference before any traffic routes
        # through it.  self_check is memoized per knob set, so fleets of
        # engines pay once per process.
        self.fused_decode = bool(fused_decode)
        if self.fused_decode:
            ok, why = kernels.fused_decode_self_check(
                self.temperature, self.top_k, self.top_p)
            if not ok:
                warnings.warn(
                    f"fused decode step disabled, falling back to the "
                    f"unfused dispatch+sample path: {why}",
                    RuntimeWarning, stacklevel=2)
                # a warning is per-process noise; the counter makes a
                # fleet-wide silent fallback visible on /metrics
                self.metrics.counter(
                    "graph_rewrite_fallbacks_total",
                    "verified-rewrite paths (fused decode) that failed "
                    "self-check and fell back to the reference path",
                ).inc()
                self.fused_decode = False
        # the span descriptors of the batch being dispatched, in logits
        # row order: (slot, kind, n_tokens) — ScriptedEngine's fake
        # compute and the one-dispatch tests read this.  _batch_out is
        # the parallel (out_start, out_len) logits layout and
        # _batch_drafts maps slot -> the drafts its verify span carries.
        self._batch_spans: List[tuple] = []
        self._batch_out: List[tuple] = []
        self._batch_drafts: dict = {}
        # accept/reject randomness (rejection sampling + host-side
        # temperature sampling while speculation is on); independent of
        # the jax key chain the non-speculative path uses
        self._spec_rng = np.random.default_rng(seed ^ 0x5bec)

        # swap path: page gather (preempt) reads the pools — NOT donated;
        # page scatter (resume) replaces them — donated like decode.  idx
        # is padded to a fixed pages_per_seq with the reserved page 0, so
        # one compiled program covers every page count
        @jax.jit
        def _swap_out(k_pool, v_pool, idx):
            out = generation.gather_kv_pages(
                {"k": k_pool, "v": v_pool}, idx)
            return out["k"], out["v"]

        self._swap_out = _swap_out

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _swap_in(k_pool, v_pool, idx, host_k, host_v):
            pools = generation.scatter_kv_pages(
                {"k": k_pool, "v": v_pool}, idx,
                {"k": host_k, "v": host_v})
            return pools["k"], pools["v"]

        self._swap_in = _swap_in

        # copy-on-write page clone: src/dst are traced int32 scalars, so
        # every COW rides ONE compiled executable (donated like decode —
        # the caller replaces cache.pools with the result)
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _cow(k_pool, v_pool, src, dst):
            pools = generation.copy_kv_page(
                {"k": k_pool, "v": v_pool}, src, dst)
            return pools["k"], pools["v"]

        self._cow = _cow

    def ragged_probe_args(self) -> tuple:
        """The ONE abstract `_ragged` arg tuple — the Graph Doctor's
        shape-poly probe.  Unlike the retired bucket menu (one compiled
        prefill per bucket), the unified step has a single signature:
        `analysis.analyze(engine._ragged, *engine.ragged_probe_args())`
        must stay clean with the default expected_signatures=1.  With
        speculation on, the SAME single signature covers verify spans:
        the batch geometry is sized once for spec_k, and out_rows grows
        to the fixed num_out — varying per-step k never adds a second
        executable."""
        pools = self.cache.pools
        T = self._num_blocks * self.block_q
        S = self._num_spans
        i32 = jnp.int32
        return (
            self.params,
            jax.ShapeDtypeStruct((T,), i32),                 # tok
            jax.ShapeDtypeStruct((T,), i32),                 # row_page
            jax.ShapeDtypeStruct((T,), i32),                 # row_off
            jax.ShapeDtypeStruct((T,), i32),                 # row_pos
            jax.ShapeDtypeStruct((self._num_blocks,), i32),  # block_seq
            jax.ShapeDtypeStruct((self._num_blocks,), i32),  # block_qpos
            jax.ShapeDtypeStruct((S,), i32),                 # span_len
            jax.ShapeDtypeStruct((S,), i32),                 # ctx_len
            jax.ShapeDtypeStruct((S, self.cache.pages_per_seq), i32),
            jax.ShapeDtypeStruct((self._num_out,), i32),     # out_rows
            jax.ShapeDtypeStruct(pools["k"].shape, pools["k"].dtype),
            jax.ShapeDtypeStruct(pools["v"].shape, pools["v"].dtype),
        )

    def ragged_fused_probe_args(self) -> tuple:
        """`ragged_probe_args` plus the threaded PRNG key, in
        `_ragged_fused` arg order — the graphlint probe for the fused
        single-dispatch decode step.  Same single-signature contract:
        the fused executable must also compile exactly once."""
        base = self.ragged_probe_args()
        key = np.asarray(self._key)
        return base[:11] + (
            jax.ShapeDtypeStruct(key.shape, key.dtype),) + base[11:]

    # -- client surface -----------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline: Optional[float] = None,
               req_id: Optional[str] = None, hop: int = 0,
               handoff: Optional[bool] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None) -> _Request:
        """Queue a request.  deadline: seconds from now; once expired the
        request resolves with DeadlineExceeded at the next step() boundary,
        whether still queued or mid-decode.  Raises QueueFull when the
        bounded pending queue is at capacity.  req_id/hop: the fleet
        trace context — the Router threads a request's id and placement
        count through retries so its cross-replica timeline stays one
        ring; direct callers may omit both (a fresh id is generated).
        handoff: may a prefill-class engine resolve this request at
        prefill_done with PrefillHandoff instead of decoding?  Defaults
        to True iff this engine's role is "prefill"; a Router passes
        False when re-placing a handoff's decode continuation (and for
        canaries), so a continuation landing on a prefill-class replica
        decodes locally instead of ping-ponging forever.
        tenant/priority: QoS labels.  The tenant keys a WFQ lane,
        per-tenant counters/SLOs and (when its config sets one) a
        per-tenant queue cap; an unknown tenant under an explicit table
        raises qos.UnknownTenant (a ValueError).  priority is clamped to
        max(request, tenant tier) — lower number = more important."""
        tname, eff_priority, tcfg = self.qos.resolve(tenant, priority)
        req = _Request(prompt, max_new_tokens, eos_id, deadline=deadline,
                       req_id=req_id, hop=hop, tenant=tname,
                       priority=eff_priority)
        req.allow_handoff = (self.role == "prefill") if handoff is None \
            else bool(handoff)
        total = req.prompt.size + req.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens={total} exceeds engine "
                f"max_seq_len={self.max_seq_len}")
        if self.cache.pages_needed(total) > self.cache.num_pages - 1:
            # the preemption guarantee rests on this: a LONE sequence must
            # always be able to grow to its worst case
            raise ValueError(
                f"request needs {self.cache.pages_needed(total)} pages but "
                f"the pool only holds {self.cache.num_pages - 1}")
        with self._cv:
            if self._stop:
                self._rq_event(req, "reject", reason="engine_stopped")
                raise EngineStopped("engine is stopped")
            t = self._thread
            if t is not None and not t.is_alive():
                # the step thread CRASHED (it exits cleanly only via
                # _stop, handled above): enqueueing would hand back a
                # handle nothing will ever resolve
                self._rq_event(req, "reject", reason="step_thread_dead")
                raise EngineStopped(
                    "engine step thread died; the engine is stopped "
                    "until a supervisor rebuilds it")
            if (self.max_pending is not None
                    and len(self._pending) >= self.max_pending):
                self._rq_event(req, "reject", reason="queue_full",
                               tenant=tname)
                raise QueueFull(
                    f"pending queue is full ({self.max_pending} requests)",
                    retry_after=1.0)
            tstats = self._tenant_state(tname)
            if (tcfg.max_pending is not None
                    and self._pending.depth(tname) >= tcfg.max_pending):
                # per-tenant verdict: ONE flooding tenant hits its own
                # cap while everyone else keeps submitting
                tstats.inc("rejected_queue_full")
                self._rq_event(req, "reject", reason="tenant_queue_full",
                               tenant=tname)
                raise QueueFull(
                    f"tenant {tname!r} pending queue is full "
                    f"({tcfg.max_pending} requests)", retry_after=1.0)
            req._engine = self
            self._pending.append(req)
            # every accepted request ends in EXACTLY one terminal counter
            # (completed/cancelled/timed_out/failed) — the registry
            # identity faults.check_invariants asserts
            self.stats["accepted"] += 1
            tstats.inc("accepted")
            self._rq_event(req, "submit", prompt_tokens=int(req.prompt.size),
                           max_new_tokens=req.max_new_tokens,
                           queue_depth=len(self._pending),
                           tenant=tname, priority=eff_priority)
            self._cv.notify()
        return req

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> List[List[int]]:
        """Synchronous convenience: submit all prompts and wait.  With the
        background loop running (start()/serve_llm) this only waits — the
        loop thread owns the cache; driving step() from a second thread
        would race slot/page allocation."""
        reqs = [self.submit(p, max_new_tokens, eos_id) for p in prompts]
        if self._thread is None:
            while not all(r.done() for r in reqs):
                if not self.step():
                    break  # no progress possible (errors already recorded)
            timeout = 0
        return [r.result(timeout=timeout) for r in reqs]

    def stats_snapshot(self) -> dict:
        """SOURCE OF TRUTH for engine counters: a copy taken under
        self._cv (every counter write holds the lock, so no torn
        multi-counter updates) plus queue/pool gauges, `uptime_s`, and
        `steps_total`.  The counters are read from the metrics registry
        — the same storage `GET /metrics` renders, so the JSON and
        Prometheus surfaces cannot drift.  The gauges are instantaneous
        reads: slot/page state is owned lock-free by the step thread, so
        a gauge can be one step fresher than the counters next to it."""
        with self._cv:
            snap = dict(self.stats)
            snap["queue_depth"] = len(self._pending)
            snap["free_pages"] = self.cache.free_page_count
            snap["free_slots"] = self.cache.free_slot_count
            snap["uptime_s"] = time.monotonic() - self._t_start
        # objective health rides the same snapshot (/stats shows SLO
        # verdicts next to the counters; the registry gauges are the
        # Prometheus twin) — computed outside _cv: the SLO engine has
        # its own lock and never touches engine state
        snap["slo"] = self.slo.report()
        # the attribution layer: per-phase time shares over the
        # profiler window, pool/fragmentation telemetry, and the
        # watchdog's verdict — /stats and /metrics expose the same
        # phase table on both serve paths
        snap["step_phases"] = self.stepprof.report()
        snap["pool"] = self.pool_snapshot()
        snap["watchdog"] = self.watchdog.report()
        snap["prefix"] = self.prefix_snapshot()
        snap["role"] = self.role
        snap["kvstore"] = (None if self.kvstore is None
                           else self.kvstore.snapshot())
        snap["tenants"] = self.tenant_snapshot()
        return snap

    def prefix_snapshot(self) -> dict:
        """The prefix-reuse section of /stats (both serve paths render
        it): hit/miss/splice/COW/eviction counters plus the index's
        live footprint.  hit_rate is cumulative hits / lookups."""
        idx = self.prefix_index
        hits = self.stats["prefix_hits"]
        misses = self.stats["prefix_misses"]
        total = hits + misses
        return {
            "enabled": idx is not None,
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / total, 4) if total else 0.0,
            "spliced_pages": self.stats["prefix_spliced_pages"],
            "cow_copies": self.stats["prefix_cow_copies"],
            "evictions": self.stats["prefix_evictions"],
            "cached_pages": 0 if idx is None else idx.cached_pages,
            "cached_prefixes": 0 if idx is None else idx.leaf_count,
            "tier_hits": self.stats["prefix_tier_hits"],
            "promoted_pages": self.stats["kv_promoted_pages"],
            "demoted_pages": self.stats["kv_demoted_pages"],
        }

    # -- multi-tenant QoS surface -------------------------------------------

    _TENANT_STAT_KEYS = ("accepted", "admitted", "completed",
                         "preempted", "emitted_tokens",
                         "rejected_queue_full")
    _TENANT_STAT_HELP = {
        "accepted": "requests this tenant got past submit()",
        "admitted": "fresh admissions of this tenant into a slot",
        "completed": "this tenant's requests finished with tokens",
        "preempted": "this tenant's slots evicted under page pressure",
        "emitted_tokens": "tokens appended to this tenant's streams",
        "rejected_queue_full": "submits refused by this tenant's "
                               "queue cap",
    }

    @staticmethod
    def _tenant_label(name: str) -> str:
        """Metric-name-safe tenant slug (labels arrive from HTTP)."""
        return re.sub(r"[^A-Za-z0-9_]", "_", str(name))

    def _tenant_state(self, name: str) -> _StatsDict:
        """This tenant's counter dict, creating its counters, queue-depth
        gauge, and per-tenant SLO engine on first sight.  Safe from any
        thread: the registry serializes metric creation, and a racing
        double-create just wins with one of two identical objects."""
        st = self._tenant_stats.get(name)
        if st is not None:
            return st
        reg = self.metrics
        label = self._tenant_label(name)
        st = _StatsDict(reg, self._TENANT_STAT_KEYS,
                        prefix=f"llm_tenant_{label}",
                        help=self._TENANT_STAT_HELP)
        reg.gauge(f"llm_tenant_{label}_queue_depth",
                  f"pending requests of tenant {name!r}").set_function(
            lambda t=name: self._pending.depth(t))
        # clone the engine's objectives under tenant-scoped names so one
        # registry carries every tenant's burn-rate gauges side by side
        objs = tuple(obs_slo.Objective(
            o.metric, o.q, o.threshold_s,
            name=f"tenant_{label}_{o.name}") for o in self.slo.objectives)
        self._tenant_slo[name] = obs_slo.SLOEngine(
            objectives=objs, window_s=self._slo_window_s).register(reg)
        self._tenant_stats[name] = st
        return st

    def _tenant_slo_observe(self, tenant: str, metric: str, value: float,
                            t=None) -> None:
        slo = self._tenant_slo.get(tenant)
        if slo is not None:
            slo.observe(metric, value, t=t)

    def tenant_snapshot(self) -> dict:
        """The per-tenant section of /stats: config, live queue depth,
        counters, and the tenant-scoped SLO report."""
        out: dict = {}
        for name in list(self._tenant_stats):
            cfg = self.qos.get(name)
            slo = self._tenant_slo.get(name)
            out[name] = {
                "priority": cfg.priority,
                "weight": cfg.weight,
                "max_pending": cfg.max_pending,
                "queue_depth": self._pending.depth(name),
                "counters": dict(self._tenant_stats[name]),
                "slo": {} if slo is None else slo.report(),
            }
        return out

    def tenant_burn_rates(self, max_priority: Optional[int] = None
                          ) -> dict:
        """{tenant: max burn rate across its objectives} over the
        rolling SLO window — the autoscaler's control signal.  With
        max_priority set, only tenants AT LEAST that important (tier
        number <= max_priority) are reported."""
        out: dict = {}
        for name, slo in list(self._tenant_slo.items()):
            if max_priority is not None \
                    and self.qos.get(name).priority > max_priority:
                continue
            rep = slo.report()
            out[name] = max(
                (o["burn_rate"] for o in rep["objectives"].values()),
                default=0.0)
        return out

    def state_digest(self) -> dict:
        """A compact, JSON-safe digest of live engine state — the
        flight recorder's "engine" section.  Read lock-free on purpose:
        the dump path runs from dying threads and the router's death
        tick, where taking engine._cv could deadlock against the very
        thread being mourned.  A digest may therefore be one step stale;
        a crash digest is exact (the step thread is gone, state is
        frozen).  Against a LIVE step thread (health-ejection dumps),
        iterating _slots/_pending can race a mutation and raise — retry
        a few times rather than hand the recorder an empty engine
        section for exactly the busy engines it matters on."""
        last_err: Optional[BaseException] = None
        for _ in range(4):
            try:
                slots = {}
                for slot in list(self._slots):
                    st = self._slots.get(slot)
                    if st is None:
                        continue
                    slots[str(slot)] = {
                        "req_id": st.req.req_id,
                        "hop": st.req.hop,
                        "ctx": int(st.ctx),
                        "tokens": len(st.req.tokens),
                        "prefilling": bool(st.prefilling),
                        "admit_seq": int(st.admit_seq),
                    }
                pending_ids = [r.req_id for r in list(self._pending)]
                return {
                    "replica": self.replica_name,
                    "role": self.role,
                    "slots": slots,
                    "pending": len(pending_ids),
                    "pending_req_ids": pending_ids,
                    "free_pages": self.cache.free_page_count,
                    "free_slots": self.cache.free_slot_count,
                    "counters": dict(self.stats),
                    "alive": self.alive(),
                    "uptime_s": time.monotonic() - self._t_start,
                }
            except RuntimeError as e:   # mutated-during-iteration race
                last_err = e
        return {"replica": self.replica_name,
                "error": f"digest raced a live step thread "
                         f"({last_err!r:.120})",
                "alive": self.alive()}

    def _compute_frag(self) -> int:
        """Fragmentation: the longest contiguous run of free page IDS
        (cached into the fields the gauges read; also returns it).
        Paged attention is random-access so this is allocator-churn
        signal (how shuffled the free list got), not a correctness
        hazard.  O(free·log free), so it runs at SCRAPE/trace time, not
        unconditionally per step — a 100k-page production pool must not
        pay a sort per decode token.  Guarded: a scrape thread can race
        the step thread mutating the free list; a torn read returns the
        last cached figure rather than crashing the render."""
        cache = self.cache
        try:
            free_pages = sorted(cache._free_pages)
        except Exception:  # noqa: BLE001 — raced a live step thread
            return self._frag_max_run
        run = best = 0
        prev = None
        for p in free_pages:
            run = run + 1 if (prev is not None and p == prev + 1) else 1
            if run > best:
                best = run
            prev = p
        self._frag_max_run = best
        self._frag_ratio = \
            (best / len(free_pages)) if free_pages else 1.0
        return best

    def _sample_telemetry(self) -> None:
        """KV-pool & scheduler memory telemetry, once per step: update
        the pool watermarks (O(1)) and, while the tracer is enabled,
        drop one sample on each Perfetto COUNTER track — free pages
        collapsing render UNDER the span that caused it.  Runs on the
        step thread (which owns the cache), so the reads are exact."""
        cache = self.cache
        free = cache.free_page_count
        used = cache.num_pages - 1 - free
        if free < self._pool_free_low_wm:
            self._pool_free_low_wm = free
        if used > self._pool_used_high_wm:
            self._pool_used_high_wm = used
        tr = self.tracer
        if tr.enabled:
            # two multi-series counter tracks per step (not one event
            # per gauge): the decode loop's allocation rate is part of
            # the obs_overhead budget.  The frag series refreshes every
            # 32 steps, not per step — honoring _compute_frag's
            # no-sort-per-token contract even with tracing left on
            self._frag_stale -= 1
            if self._frag_stale <= 0:
                self._compute_frag()
                self._frag_stale = 32
            tr.counter("pool_pages", {"free": free, "used": used,
                                      "frag_run": self._frag_max_run})
            sched = {"queue": len(self._pending),
                     "slots": len(self._slots),
                     "batch_tokens": self._last_batch_tokens}
            if self.spec_k:
                drafted = self.stats["spec_drafted"]
                sched["spec_acceptance"] = (
                    (self.stats["spec_accepted"] / drafted)
                    if drafted else 1.0)
            tr.counter("sched", sched)
            if self.prefix_index is not None:
                # cached-page footprint next to the pool track: splices
                # and COW copies render under the step that caused them
                tr.counter("prefix", {
                    "cached_pages": self.prefix_index.cached_pages,
                    "hits": self.stats["prefix_hits"],
                    "spliced_pages": self.stats["prefix_spliced_pages"],
                    "cow_copies": self.stats["prefix_cow_copies"],
                })
            if self.kvstore is not None or self.role != "mixed":
                # the disaggregation/tier track: handoff traffic and
                # host-tier flow render under the transfer phase spans
                tr.counter("transfer", {
                    "pages": self.stats["kv_transfer_pages"],
                    "bytes": self.stats["kv_transfer_bytes"],
                    "demoted": self.stats["kv_demoted_pages"],
                    "promoted": self.stats["kv_promoted_pages"],
                })

    def pool_snapshot(self) -> dict:
        """The memory-telemetry section of /stats: pool occupancy,
        watermarks, fragmentation, per-slot page counts, and the last
        ragged batch's token count.  Instantaneous lock-free reads
        (same freshness contract as the gauges); the slot-page table is
        step-thread-owned, so (like state_digest) reading it from a
        scrape thread retries the mutated-during-iteration race instead
        of failing the /stats request."""
        cache = self.cache
        slot_pages: dict = {}
        for _ in range(4):
            try:
                slot_pages = {str(s): len(p) for s, p in
                              list(cache._slot_pages.items())}
                break
            except RuntimeError:        # raced a live step thread
                continue
        free = cache.free_page_count
        return {
            "pages_total": cache.num_pages - 1,
            "page_size": cache.page_size,
            "free_pages": free,
            "used_pages": cache.num_pages - 1 - free,
            "free_low_watermark": self._pool_free_low_wm,
            "used_high_watermark": self._pool_used_high_wm,
            "frag_max_run": self._compute_frag(),
            "frag_ratio": round(self._frag_ratio, 4),
            "slot_pages": slot_pages,
            "batch_tokens_last": self._last_batch_tokens,
        }

    def _rq_event(self, req: _Request, name: str, **attrs) -> None:
        """One request-timeline edge, stamped with this replica's name
        and the request's hop.  One branch when the registry is
        disabled."""
        rt = self.reqtrace
        if rt is not None and rt.enabled:
            rt.event(req.req_id, name, replica=self.replica_name,
                     hop=req.hop, **attrs)

    def latency_snapshot(self) -> dict:
        """Per-request latency percentiles over the recent raw-sample
        window (exact, not bucket-interpolated): {"ttft_s",
        "inter_token_s", "queue_wait_s", "tokens_per_sec"} each carrying
        {p50, p99, n}.  The public face of the lifecycle histograms —
        bench.py and routers consume this, not the private fields."""
        out = {}
        for key, hist in (("ttft_s", self._h_ttft),
                          ("inter_token_s", self._h_itl),
                          ("queue_wait_s", self._h_queue_wait),
                          ("tokens_per_sec", self._h_tps)):
            samples = hist.samples()
            out[key] = {"p50": obs_metrics.percentile(samples, 0.5),
                        "p99": obs_metrics.percentile(samples, 0.99),
                        "n": len(samples)}
        return out

    # -- engine loop --------------------------------------------------------

    # threadlint: atomic — advisory lock-free peek: routers and the idle
    # wait use it as a wakeup hint; _loop re-checks under _cv before
    # acting, so a torn _pending/_kv_imports view only costs a spin
    def has_work(self) -> bool:
        return bool(self._pending or self._slots or self._kv_imports)

    def alive(self) -> bool:
        """Step-thread liveness, the signal the fleet Router's health
        probes and the EngineSupervisor read: False once shut down OR
        once a started step thread died (crash/stranded state).  An
        engine that was never start()ed counts as alive — it is driven
        by explicit step() calls."""
        if self._stop:
            return False
        t = self._thread
        return t is None or t.is_alive()

    def step(self) -> bool:
        """One engine iteration: reap cancelled/expired requests, admit
        pending requests into free slots (resuming preempted ones first —
        they re-enter at the queue head), then advance EVERY active slot
        through ONE ragged dispatch — decode spans and prefill chunks in
        the same batch (preempting victims when page allocation fails) —
        and evict finished sequences.  Returns True when any work was
        done."""
        self.stats["steps_total"] += 1
        # named fault point for the step loop itself: an InjectedFault
        # here is caught by _loop's backstop (fails in-flight, keeps
        # serving); an InjectedCrash (BaseException) escapes it and KILLS
        # the step thread with handles stranded and slots held — the
        # replica-death shape the fleet tier must survive
        self._fire("step")
        prof = self.stepprof
        # an armed watchdog must keep evaluating even with the profiler
        # off (no phase record to feed on) — it then times the step
        # itself and attribution degrades to an empty guilty list
        t0 = (time.perf_counter()
              if self.watchdog.enabled and not prof.enabled else None)
        with self.tracer.span("engine_step"):
            with prof.step() as pstep:
                # drain queued KV imports FIRST: a handoff's pages must
                # be in the prefix index before its continuation request
                # (queued right behind the import) reaches _admit's
                # splice — same step, zero extra latency
                imported = self._drain_imports()
                with prof.phase("schedule"):
                    reaped = self._reap()
                    admitted = self._admit()
                stepped = self._ragged_step()
        self._sample_telemetry()
        rec = getattr(pstep, "record", None)
        if rec is not None:
            # the watchdog feeds on the frame the profiler just closed;
            # a sustained spike drops a step_anomaly dump through the
            # flight seam with the per-phase deltas attached
            self.watchdog.observe_step(rec["total_s"], rec["phases"],
                                       flight=self.flight)
        elif t0 is not None:
            self.watchdog.observe_step(time.perf_counter() - t0, None,
                                       flight=self.flight)
        return reaped or admitted or stepped or imported

    def start(self):
        """Run the engine loop in a background thread (serving mode)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def shutdown(self, timeout: float = 10.0):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            # a mid-step thread owns the cache: releasing slots/pages under
            # it would hand the same pages to two sequences.  Re-join once
            # (a long decode step can outlive the first timeout), then
            # REFUSE to touch slot/page state while it is still alive.
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                err = EngineStopped("engine shut down (step thread wedged)")
                with self._cv:
                    for req in list(self._pending):
                        self.stats["failed"] += 1
                        req._resolve(err)
                    self._pending.clear()
                raise RuntimeError(
                    f"engine step thread still running after "
                    f"{2 * timeout:.0f}s; queued requests were failed but "
                    "slots/pages were NOT released (the thread owns them) — "
                    "retry shutdown() once it finishes its step")
            self._thread = None
        # thread is gone (or never ran): fail anything still queued or in
        # flight so waiters unblock, and reclaim the slots.  Under _cv: a
        # client thread's cancel() also removes/resolves pending requests,
        # and racing it here would double-resolve a handle.  EngineStopped
        # (a RuntimeError) so the fleet Router classifies these as replica
        # death and retries the zero-token ones elsewhere.
        err = EngineStopped("engine shut down")
        with self._cv:
            for req in list(self._pending):
                # terminal-counter identity (accepted == sum of outcomes)
                # holds through shutdown: force-resolved counts as failed
                self.stats["failed"] += 1
                self._rq_event(req, "resolve", outcome="engine_stopped",
                               queued=True)
                req._resolve(err)
            self._pending.clear()
            for slot in list(self._slots):
                # threadlint: atomic — safe off the owner thread: the
                # step thread is joined (or never ran) by this point
                st = self._slots.pop(slot)
                self.stats["failed"] += 1
                self._rq_event(st.req, "resolve",
                               outcome="engine_stopped",
                               tokens=len(st.req.tokens))
                st.req._resolve(err)
                self.cache.release_slot(slot)

    def _loop(self):
        while True:
            with self._cv:
                while not self._stop and not self.has_work():
                    self._cv.wait()
                if self._stop:
                    return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — backstop: step()
                # handles its own dispatch faults; anything escaping is an
                # engine bug — fail in-flight work so waiters unblock
                self._fail_inflight(e)
            except BaseException as e:  # noqa: BLE001 — InjectedCrash
                # (chaos) or interpreter teardown: the step thread dies
                # RIGHT HERE with slots held and handles unresolved.  No
                # cleanup by design — this is replica death, the shape
                # the fleet supervisor must prove it recovers from
                # (shutdown() on the dead engine resolves the strands;
                # the Router re-places what is safely recoverable).  The
                # ONE thing the dying thread does is drop the black box:
                # the flight recorder dumps the pre-crash state digest,
                # recent spans, and counters — dump() never raises.
                fl = self.flight
                if fl is not None:
                    fl.dump("step_thread_death", error=e)
                return

    def _recover_pools(self, cause: BaseException) -> bool:
        """If a failed donated dispatch consumed the k/v pools, re-zero
        them and fail every in-flight slot (their cached KV is gone).
        Returns True when recovery ran.  No-op while the buffers are
        alive (CPU, or a failure before dispatch)."""
        cache = self.cache
        try:
            dead = any(getattr(a, "is_deleted", lambda: False)()
                       for a in (cache.pools["k"], cache.pools["v"]))
        except Exception:  # noqa: BLE001 — treat unknown state as dead
            dead = True
        if not dead:
            return False
        err = RuntimeError(f"KV pools lost to a failed donated dispatch "
                           f"({cause!r:.120}); slot state was reset")
        for slot in list(self._slots):
            self._evict(slot, err, "failed")
        # NO cached prefix survives pool deallocation: the index's pages
        # are about to hold zeroed KV — serving a splice from them would
        # be silent corruption.  Drop every reference before re-zeroing.
        if self.prefix_index is not None:
            self.prefix_index.clear()
        cache.pools = generation.init_paged_kv_pools(
            self.config, cache.num_pages, cache.page_size)
        return True

    # -- internals ----------------------------------------------------------

    def _fire(self, point: str, **ctx) -> None:
        if self.faults is not None:
            self.faults.fire(point, engine=self, **ctx)

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _sample(self, logits):
        return generation.sample_logits(
            logits, self._next_key(), self.temperature, self.top_k,
            self.top_p)

    def _reap(self) -> bool:
        """Resolve cancelled and past-deadline requests, queued or in
        flight, releasing any slot/pages they hold."""
        now = time.monotonic()
        did = False
        with self._cv:
            for req in list(self._pending):
                if req.cancelled:
                    err = RequestCancelled("request cancelled")
                    key = "cancelled"
                elif req.deadline is not None and now >= req.deadline:
                    err = DeadlineExceeded("deadline expired while queued")
                    key = "timed_out"
                else:
                    continue
                self._pending.remove(req)
                self.stats[key] += 1
                self._rq_event(req, "resolve", outcome=key, queued=True)
                req._resolve(err)
                did = True
        for slot in list(self._slots):
            st = self._slots.get(slot)
            if st is None:
                continue
            if st.req.cancelled:
                self._evict(slot, RequestCancelled("request cancelled"),
                            "cancelled")
                did = True
            elif st.req.deadline is not None and now >= st.req.deadline:
                self._evict(slot, DeadlineExceeded(
                    f"deadline expired after {len(st.req.tokens)} tokens"),
                    "timed_out")
                did = True
        return did

    def _evict(self, slot: int, err: BaseException, stat_key: str) -> None:
        st = self._slots.pop(slot)
        self.cache.release_slot(slot)
        self.tracer.instant("evict", slot=slot, reason=stat_key)
        with self._cv:
            self.stats[stat_key] += 1
        self._rq_event(st.req, "resolve", outcome=stat_key,
                       tokens=len(st.req.tokens))
        st.req._resolve(err)

    def _pick_victim(self) -> int:
        """Preemption ladder: victims come from the LEAST important
        priority tier first (higher tier number), and only within a tier
        does the configured policy pick — so a flooding low-priority
        tenant's slots absorb all the page pressure before any
        high-priority slot is touched.  (Cached prefixes were already
        reclaimed before this runs, lowest tier first — see
        PrefixIndex.evict.)"""
        if self.victim_policy == "fewest_tokens":
            # least work lost; tie -> latest admitted
            return min(self._slots, key=lambda s: (
                -self._slots[s].req.priority,
                len(self._slots[s].req.tokens), -self._slots[s].admit_seq))
        return max(self._slots, key=lambda s: (
            self._slots[s].req.priority, self._slots[s].admit_seq))

    def _preempt(self, slot: int) -> None:
        """Release a victim's pages and re-queue it at the HEAD of the
        pending deque, carrying a host copy of its KV pages (swap mode)
        or nothing (recompute mode: the whole context re-enters as a
        ragged prefill).  Mid-prefill victims are handled the same way —
        swap carries the chunks already cached, recompute starts the
        span over."""
        cache = self.cache
        st = self._slots.pop(slot)
        pages = list(cache._slot_pages[slot])
        if self.preempt_mode == "swap":
            rs = _ResumeState(ctx=st.ctx, last_tok=st.last_tok,
                              n_pages=len(pages), pending=st.pending,
                              sample_on_finish=st.sample_on_finish)
        elif st.pending is not None:
            # recompute, mid-prefill: nothing sampled yet past `pending`;
            # the whole span just re-prefills from scratch
            rs = _ResumeState(ctx=0, last_tok=st.last_tok, n_pages=0,
                              pending=st.pending,
                              sample_on_finish=st.sample_on_finish)
        else:
            # recompute, decoding: the cached context is prompt + all
            # generated tokens except the still-pending one — resume
            # appends it to later ragged batches as chunked spans
            ids = np.concatenate(
                [st.req.prompt, np.asarray(st.req.tokens[:-1], np.int32)])
            rs = _ResumeState(ctx=0, last_tok=st.last_tok, n_pages=0,
                              pending=ids, sample_on_finish=False)
        self.tracer.instant("preempt", slot=slot, ctx=st.ctx,
                            mode=self.preempt_mode,
                            mid_prefill=st.prefilling)
        self._rq_event(st.req, "preempt", slot=slot, ctx=st.ctx,
                       mode=self.preempt_mode,
                       mid_prefill=st.prefilling)
        try:
            if self.preempt_mode == "swap" and pages:
                with self.tracer.span("swap_out", slot=slot,
                                      pages=len(pages)), \
                     self.stepprof.phase("swap"):
                    self._fire("swap_out", slot=slot, pools=cache.pools)
                    idx = generation.pad_page_idx(pages,
                                                  cache.pages_per_seq)
                    hk, hv = self._swap_out(cache.pools["k"],
                                            cache.pools["v"],
                                            jnp.asarray(idx))
                    rs.host_k = np.asarray(hk)   # device -> host RAM
                    rs.host_v = np.asarray(hv)
                with self._cv:
                    self.stats["swap_out_pages"] += len(pages)
        except Exception as e:  # noqa: BLE001 — a failed swap-out loses the
            # victim's KV: fail that request, keep the engine serving
            cache.release_slot(slot)
            with self._cv:
                self.stats["failed"] += 1
            st.req._resolve(e)
            self._recover_pools(e)
            return
        cache.release_slot(slot)
        st.req._resume = rs
        with self._cv:
            self._pending.appendleft(st.req)
            self.stats["preemptions"] += 1
            self._tenant_state(st.req.tenant).inc("preempted")

    def _admit(self) -> bool:
        """Move pending requests into free slots.  Admission itself
        dispatches NOTHING for fresh and recompute-resumed requests —
        their tokens enter the next unified ragged batch as chunked
        spans; only a swap-resume scatters its host KV copy back."""
        cache = self.cache
        progress = False
        while True:
            with self._cv:
                if not self._pending or cache.free_slot_count == 0:
                    break
                req = self._pending[0]
                rs = req._resume
                if rs is not None and rs.host_k is not None:
                    need = rs.n_pages
                else:
                    pend = (rs.pending if rs is not None else req.prompt)
                    need = cache.pages_needed(
                        min(pend.size, self.prefill_chunk_tokens))
                if need > cache.free_page_count:
                    # cached-but-unreferenced prefixes count as admission
                    # headroom: reclaim before stalling the queue on them
                    self._reclaim_pages(need - cache.free_page_count)
                if need > cache.free_page_count:
                    break  # head-of-line waits for pages (no reordering)
                self._pending.popleft()
            slot = cache.acquire_slot()
            self._admit_seq += 1
            if req.cancelled:   # cancelled between submit and admission
                cache.release_slot(slot)
                with self._cv:
                    self.stats["cancelled"] += 1
                req._resolve(RequestCancelled("request cancelled"))
                progress = True
                continue
            try:
                with self.tracer.span("admit", slot=slot,
                                      resume=rs is not None):
                    if rs is not None:
                        self._resume_into(slot, req, rs)
                    else:
                        if req.t_admit is None:
                            req.t_admit = time.monotonic()
                            wait = req.t_admit - req.t_submit
                            self._h_queue_wait.observe(wait)
                            self.slo.observe("queue_wait", wait)
                            self._tenant_slo_observe(
                                req.tenant, "queue_wait", wait)
                        # prefix-hit admission: splice the cached pages
                        # and start ctx past them — the next ragged
                        # batches chunk-prefill only the unshared suffix
                        ctx0 = self._splice_prefix(slot, req.prompt)
                        self._slots[slot] = _SlotState(
                            req, self._admit_seq, ctx=ctx0,
                            pending=req.prompt, sample_on_finish=True,
                            spec_k=self.spec_k)
                        with self._cv:
                            self.stats["admitted"] += 1
                            self._tenant_state(req.tenant).inc("admitted")
                        self._rq_event(req, "admit", slot=slot,
                                       prefix_tokens=ctx0,
                                       tenant=req.tenant,
                                       priority=req.priority)
            except Exception as e:  # noqa: BLE001 — admission must not leak
                # the request left _pending but never (or only briefly)
                # reached _slots: without cleanup the slot and its pages
                # leak forever and result() blocks until timeout.  Release
                # both, resolve the handle with the error, and keep
                # admitting — a per-request failure must not wedge the
                # engine.
                self._slots.pop(slot, None)
                if slot in cache._slot_pages:
                    cache.release_slot(slot)
                with self._cv:
                    self.stats["failed"] += 1
                req._resolve(e)
                # _swap_in DONATES the pools: a dispatch that fails after
                # donation has already consumed them (TPU; CPU ignores
                # donation), and every later dispatch would die on
                # deleted buffers.  Re-zero the pools and fail the slots
                # whose KV lived in them.
                self._recover_pools(e)
            progress = True
        return progress

    def _resume_into(self, slot: int, req: _Request,
                     rs: _ResumeState) -> None:
        """Re-admit a preempted request.  Swap mode reallocates its page
        count and scatters the host KV copy back (bit-identical cache);
        recompute mode just installs the whole context as the slot's
        pending span — the next ragged batches re-prefill it through the
        SAME chunked math a fresh prompt uses, so both modes stay
        token-exact."""
        cache = self.cache
        if rs.host_k is not None:
            self._fire("page_alloc", slot=slot,
                       n_tokens=rs.n_pages * cache.page_size)
            cache.ensure_capacity(slot, rs.n_pages * cache.page_size)
            with self.tracer.span("swap_in", slot=slot,
                                  pages=rs.n_pages) as sp, \
                 self.stepprof.phase("swap") as ph:
                self._fire("swap_in", slot=slot, pools=cache.pools)
                idx = generation.pad_page_idx(cache._slot_pages[slot],
                                              cache.pages_per_seq)
                k_pool, v_pool = self._swap_in(
                    cache.pools["k"], cache.pools["v"], jnp.asarray(idx),
                    jnp.asarray(rs.host_k), jnp.asarray(rs.host_v))
                sp.fence(k_pool)
                ph.fence(k_pool)
            cache.pools = {"k": k_pool, "v": v_pool}
            with self._cv:
                self.stats["swapped_in"] += 1
                self.stats["swap_in_pages"] += rs.n_pages
        with self._cv:
            self.stats["resumed"] += 1
        req._resume = None
        ctx0 = rs.ctx
        if rs.host_k is None and ctx0 == 0 and rs.pending is not None:
            # recompute-resume re-prefills the whole context — a cached
            # prefix (usually its own prompt, registered before the
            # preemption) shrinks that to the unshared suffix, token-
            # exactly: spliced pages hold the identical positions' KV
            ctx0 = self._splice_prefix(slot, rs.pending)
        self._slots[slot] = _SlotState(
            req, self._admit_seq, ctx=ctx0, last_tok=rs.last_tok,
            pending=rs.pending, sample_on_finish=rs.sample_on_finish,
            spec_k=self.spec_k)
        self._rq_event(req, "resume", slot=slot, ctx=rs.ctx,
                       mode=("swap" if rs.host_k is not None
                             else "recompute"))

    def _reclaim_pages(self, need: int, prefer_page: Optional[int] = None
                       ) -> int:
        """Evict cached-but-unreferenced prefixes (LRU) to free `need`
        pages — ALWAYS tried before preempting a live sequence, so the
        prefix cache rides slack capacity and never costs anyone real
        work.  prefer_page: under copy-on-write pressure, first drop the
        index's own ref on that page (making it private beats copying
        it).  Returns pages actually returned to the free pool."""
        idx = self.prefix_index
        if idx is None:
            return 0
        freed = 0
        if prefer_page is not None:
            freed += idx.evict_subtree_holding(prefer_page)
        if freed < need:
            freed += idx.evict(need - freed)
        evicted = idx.evicted_pages_total - self._prefix_evicted_seen
        if evicted > 0:
            self._prefix_evicted_seen = idx.evicted_pages_total
            with self._cv:
                self.stats["prefix_evictions"] += evicted
            self.tracer.instant("prefix_evict", pages=evicted)
        return freed

    def _alloc_with_preemption(self, slot: int, n_tokens: int) -> bool:
        """Grow `slot`'s pages to cover n_tokens, reclaiming cached
        prefixes and then preempting victims under pressure.  Never
        preempts the last runnable sequence (its worst case was validated
        at submit), so a lone request always completes.  Returns False
        when `slot` itself was preempted or evicted."""
        cache = self.cache
        while True:
            try:
                self._fire("page_alloc", slot=slot, n_tokens=n_tokens)
                cache.ensure_capacity(slot, n_tokens)
                return True
            except RuntimeError as e:
                # cached prefixes are the cheapest memory on the machine:
                # evict them (LRU) before touching a live sequence
                if self._reclaim_pages(
                        max(1, cache.pages_needed(n_tokens)
                            - len(cache._slot_pages.get(slot, ()))
                            - cache.free_page_count)):
                    continue
                if len(self._slots) == 1:
                    # last runnable: a pool too small for one sequence is
                    # rejected at submit(), so this is an injected or
                    # configuration fault — fail the request rather than
                    # deadlock
                    self._evict(slot, e, "failed")
                    return False
                victim = self._pick_victim()
                self._preempt(victim)
                if victim == slot or slot not in self._slots:
                    # preempted ourselves — or a failed swap-out
                    # recovered the pools and failed this slot too
                    return False

    def _splice_prefix(self, slot: int, tokens) -> int:
        """Admission-time prefix splice: look the prompt/context up in
        the radix index and install the longest cached prefix's pages
        into the fresh slot — page-table bookkeeping only, NO dispatch.
        At least one token is always left to prefill (the finishing span
        must produce logits).  Returns the spliced token count (the
        slot's starting ctx)."""
        idx = self.prefix_index
        if idx is None:
            return 0
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        matched, pages = (0, []) if tokens.size < 2 else \
            idx.lookup(tokens, tokens.size - 1)
        # host-tier extension: where the device chain ends PAGE-ALIGNED,
        # demoted-but-warm pages can extend it — promoted back through
        # the one compiled _swap_in scatter (no new executables)
        if self.kvstore is not None \
                and matched % self.cache.page_size == 0:
            matched, pages = self._promote_from_host(tokens, matched,
                                                     pages)
        # a sub-page match is a net loss: the splice would save < one
        # page of prefill but cost a whole-page copy the moment the
        # slot appends into the shared page — treat it as a miss
        if matched < self.cache.page_size or not pages:
            with self._cv:
                self.stats["prefix_misses"] += 1
            return 0
        self.cache.splice_pages(slot, pages)
        with self._cv:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_spliced_pages"] += len(pages)
        self.tracer.instant("prefix_splice", slot=slot, tokens=matched,
                            pages=len(pages))
        return matched

    def _register_prefix(self, slot: int, st: "_SlotState") -> None:
        """A slot just finished prefilling: its FULL pages become cached
        prefix — the index takes a reference on each, so the KV survives
        this slot's release and later admissions splice it.  The partial
        tail page is deliberately NOT registered here: the slot itself
        appends into it on its very next decode step, and sharing it now
        would force a copy-on-write the request pays for its own page —
        it registers at completion instead (`_finish`), when no more
        appends can land in it."""
        idx = self.prefix_index
        if idx is None or st.pending is None:
            return
        ps = self.cache.page_size
        n_full = st.ctx - st.ctx % ps
        if n_full:
            idx.insert(st.pending, n_full,
                       self.cache._slot_pages[slot][:n_full // ps],
                       tier=st.req.priority)

    # -- disaggregation & the tiered prefix store ---------------------------

    def attach_kvstore(self, store) -> None:
        """Bind a `kvstore.TieredPrefixStore` as the host tier under the
        device prefix index: LRU eviction DEMOTES a dying page's KV into
        it instead of discarding, and admission-time splicing PROMOTES
        warm pages back.  Reattachable on purpose — the fleet Router
        shares one store across replicas and re-binds it to a rebuilt
        replica after a crash, which is exactly how a cold-restarted
        replica warms its device cache from prefixes its predecessor
        demoted."""
        self.kvstore = store
        if store is None:
            if self.prefix_index is not None:
                self.prefix_index.on_evict = None
            return
        if store.page_size is None:
            store.page_size = self.cache.page_size
        elif int(store.page_size) != self.cache.page_size:
            raise ValueError(
                f"kvstore page_size={store.page_size} does not match "
                f"engine page_size={self.cache.page_size}")
        if self.prefix_index is not None:
            self.prefix_index.on_evict = self._demote_node

    def _demote_node(self, node) -> None:
        """PrefixIndex.on_evict hook: the index is about to release its
        LAST reference on `node`'s page — gather the page's KV to host
        through the one compiled `_swap_out` executable and hand it to
        the tiered store, keyed by the full token prefix.  Best-effort
        by contract (the index swallows exceptions and frees the page
        regardless); runs on the step thread, which owns the pools."""
        store = self.kvstore
        if store is None:
            return
        cache = self.cache
        prefix_full = self.prefix_index.full_prefix(node)
        with self.tracer.span("kv_demote", page=node.page), \
             self.stepprof.phase("transfer"):
            idx = generation.pad_page_idx([node.page],
                                          cache.pages_per_seq)
            hk, hv = self._swap_out(cache.pools["k"], cache.pools["v"],
                                    jnp.asarray(idx))
            hk, hv = np.asarray(hk), np.asarray(hv)
            # slice the single real page out of the fixed staging shape
            # (axis 1 is the page axis; scripted engines return opaque
            # 1-D stubs, stored as-is)
            k_page = hk[:, 0] if hk.ndim > 1 else hk
            v_page = hv[:, 0] if hv.ndim > 1 else hv
            if store.put(prefix_full, k_page, v_page,
                         tier=getattr(node, "tier", 1)):
                with self._cv:
                    self.stats["kv_demoted_pages"] += 1

    def _promote_from_host(self, tokens, matched: int, pages: list):
        """Extend a page-aligned device-tier match with host-tier pages:
        walk the store key-by-key past `matched`, scatter every page
        found through ONE `_swap_in` dispatch (the same compiled
        executable the preempt/resume path uses — zero new programs),
        and register the extended chain in the device index so later
        admissions hit it directly.  Returns the (possibly extended)
        (matched, pages); on any failure it degrades to the device-tier
        result — promotion must never fail an admission."""
        store = self.kvstore
        cache = self.cache
        ps = cache.page_size
        limit = tokens.size - 1     # >= 1 token must remain to prefill
        toks = [int(t) for t in tokens]
        found: list = []
        pos = matched
        while pos + ps <= limit \
                and len(pages) + len(found) < cache.pages_per_seq:
            kv = store.get(tuple(toks[:pos + ps]))
            if kv is None:
                break
            found.append(kv)
            pos += ps
        if not found:
            return matched, pages
        n = len(found)
        new_pages: list = []
        try:
            with self.tracer.span("kv_promote", pages=n), \
                 self.stepprof.phase("transfer") as ph:
                self._fire("kv_transfer", pools=cache.pools, pages=n,
                           direction="promote")
                if n > cache.free_page_count:
                    self._reclaim_pages(n - cache.free_page_count)
                new_pages = cache.alloc_pages(n)
                pk = cache.pools["k"]
                stage = (pk.shape[0], cache.pages_per_seq) \
                    + tuple(pk.shape[2:])
                hk = np.zeros(stage, pk.dtype)
                hv = np.zeros(stage, pk.dtype)
                for i, (kp, vp) in enumerate(found):
                    hk[:, i] = kp
                    hv[:, i] = vp
                idx = generation.pad_page_idx(new_pages,
                                              cache.pages_per_seq)
                k_pool, v_pool = self._swap_in(
                    cache.pools["k"], cache.pools["v"],
                    jnp.asarray(idx), jnp.asarray(hk), jnp.asarray(hv))
                ph.fence(k_pool)
                cache.pools = {"k": k_pool, "v": v_pool}
        except Exception as e:  # noqa: BLE001 — degrade, never fail
            for p in new_pages:
                try:
                    cache.drop_ref(p)
                except Exception:  # noqa: BLE001
                    pass
            if self._recover_pools(e):
                # recovery cleared the prefix index: the device-tier
                # pages we matched were freed with it — cold prefill
                return 0, []
            return matched, pages
        # hand ownership to the index: insert refs every page of the
        # extended chain, then drop the allocation refs — promoted
        # pages end index-owned exactly like demote's inverse
        all_pages = list(pages) + new_pages
        self.prefix_index.insert(tokens, pos, all_pages)
        for p in new_pages:
            cache.drop_ref(p)
        with self._cv:
            self.stats["kv_promoted_pages"] += n
            self.stats["prefix_tier_hits"] += 1
            self.stats["kv_transfer_pages"] += n
        self.tracer.instant("kv_promoted", pages=n,
                            tokens=pos - matched)
        return pos, all_pages

    def import_prefix(self, handoff) -> None:
        """Queue a `kvstore.KVHandoff` for import into this engine's
        prefix index.  Thread-safe and non-blocking: the payload rides
        host RAM until the STEP THREAD drains it (pool mutation is
        step-thread-owned), which happens at the top of the next step —
        before admission, so a continuation request submitted right
        after this call splices the imported pages.  Import failure
        degrades to a cold prefill; it never fails a request."""
        with self._cv:
            if self._stop:
                raise EngineStopped("engine is stopped")
            self._kv_imports.append(handoff)
            self._cv.notify()

    def _drain_imports(self) -> bool:
        """Step thread: import every queued KV handoff."""
        if not self._kv_imports:
            return False
        did = False
        while True:
            with self._cv:
                if not self._kv_imports:
                    break
                h = self._kv_imports.popleft()
            self._import_handoff(h)
            did = True
        return did

    def _import_handoff(self, h) -> int:
        """Scatter one handoff's pages into the pool and register them
        in the prefix index (the decode half of a prefill->decode
        transfer).  Returns pages imported; 0 means the continuation
        cold-prefills — correct, only slower."""
        idx_obj = self.prefix_index
        if idx_obj is None or h.n_pages == 0:
            return 0
        cache = self.cache
        pages: list = []
        try:
            with self.tracer.span("kv_transfer_in", pages=h.n_pages,
                                  src=h.src_replica or ""), \
                 self.stepprof.phase("transfer") as ph:
                self._fire("kv_transfer", pools=cache.pools,
                           pages=h.n_pages, direction="import")
                if h.n_pages > cache.free_page_count:
                    self._reclaim_pages(h.n_pages
                                        - cache.free_page_count)
                pages = cache.alloc_pages(h.n_pages)
                idx = generation.pad_page_idx(pages,
                                              cache.pages_per_seq)
                k_pool, v_pool = self._swap_in(
                    cache.pools["k"], cache.pools["v"],
                    jnp.asarray(idx),
                    jnp.asarray(h.host_k), jnp.asarray(h.host_v))
                ph.fence(k_pool)
                cache.pools = {"k": k_pool, "v": v_pool}
        except Exception as e:  # noqa: BLE001 — degrade to cold prefill
            for p in pages:
                try:
                    cache.drop_ref(p)
                except Exception:  # noqa: BLE001
                    pass
            self._recover_pools(e)
            self.tracer.instant("kv_transfer_in_failed",
                                pages=h.n_pages)
            return 0
        # ownership handshake mirrors _promote_from_host: insert refs
        # the registered pages, the allocation refs then drop — a page
        # DEDUPED against an existing node frees right here instead of
        # leaking with refcount 1
        idx_obj.insert(h.tokens, h.n_tokens, pages)
        for p in pages:
            cache.drop_ref(p)
        with self._cv:
            self.stats["kv_transfer_pages"] += h.n_pages
            self.stats["kv_transfer_bytes"] += h.nbytes
        return h.n_pages

    def _handoff_slot(self, slot: int, st: "_SlotState") -> None:
        """Prefill-class resolution: the slot just finished prefilling —
        gather its full pages to host staging (`_swap_out`, the same
        compiled executable preemption uses), release the slot, and
        resolve the request with `PrefillHandoff` carrying the payload.
        ZERO tokens are emitted (sampling happens decode-side), so the
        Router's retry rule covers every failure mode: this replica
        dying mid-transfer strands nothing the fleet cannot re-place."""
        cache = self.cache
        req = st.req
        ps = cache.page_size
        n_full = st.ctx - st.ctx % ps
        n_pages = n_full // ps
        pages = list(cache._slot_pages[slot][:n_pages])
        hk = hv = None
        try:
            with self.tracer.span("kv_transfer_out", slot=slot,
                                  pages=n_pages), \
                 self.stepprof.phase("transfer") as ph:
                self._fire("kv_transfer", slot=slot, pools=cache.pools,
                           pages=n_pages, direction="export")
                if n_pages:
                    idx = generation.pad_page_idx(
                        pages, cache.pages_per_seq)
                    dk, dv = self._swap_out(cache.pools["k"],
                                            cache.pools["v"],
                                            jnp.asarray(idx))
                    ph.fence(dk)
                    hk, hv = np.asarray(dk), np.asarray(dv)
        except Exception as e:  # noqa: BLE001 — a failed export fails
            # THIS request like any dispatch fault; the engine serves on
            self._evict(slot, e, "failed")
            self._recover_pools(e)
            return
        h = _kvstore.KVHandoff(req.prompt, n_full, n_pages, hk, hv,
                               src_replica=self.replica_name)
        del self._slots[slot]
        cache.release_slot(slot)
        with self._cv:
            self.stats["handoffs"] += 1
            self.stats["kv_transfer_pages"] += n_pages
            self.stats["kv_transfer_bytes"] += h.nbytes
        self._rq_event(req, "handoff", slot=slot, pages=n_pages,
                       tokens=n_full)
        req._resolve(PrefillHandoff(h))

    def _make_writable(self, slot: int, st: "_SlotState") -> bool:
        """Copy-on-write before the slot's next span writes at position
        st.ctx: if the page holding that position is SHARED (spliced
        prefix / index-retained), clone it privately through the one
        compiled `_cow` executable.  Under pool pressure the copy first
        reclaims cached prefixes (dropping the index's ref on the very
        source page makes it private for free), then preempts like any
        allocation.  Returns False when `slot` was evicted/preempted."""
        cache = self.cache
        i = st.ctx // cache.page_size
        pages = cache._slot_pages.get(slot)
        if pages is None or i >= len(pages) \
                or cache.refcount(pages[i]) <= 1:
            return True
        while True:
            try:
                plan = cache.cow_page(slot, i)
                break
            except RuntimeError as e:
                freed = self._reclaim_pages(1, prefer_page=pages[i])
                if cache.refcount(pages[i]) <= 1:
                    # the index dropped its ref on the source: the page
                    # is private now even if nothing returned to the
                    # pool — no copy needed at all
                    return True
                if freed:
                    continue
                if len(self._slots) == 1:
                    self._evict(slot, e, "failed")
                    return False
                victim = self._pick_victim()
                self._preempt(victim)
                if victim == slot or slot not in self._slots:
                    return False
        if plan is None:
            return True
        src, dst = plan
        try:
            k_pool, v_pool = self._cow(
                cache.pools["k"], cache.pools["v"],
                jnp.int32(src), jnp.int32(dst))
        except Exception as e:  # noqa: BLE001 — a failed donated copy is
            # a dispatch fault: the pools may be consumed, so fail
            # in-flight work and recover, exactly like the ragged step
            self._fail_inflight(e)
            return False
        cache.pools = {"k": k_pool, "v": v_pool}
        with self._cv:
            self.stats["prefix_cow_copies"] += 1
        self.tracer.instant("cow_copy", slot=slot, src=src, dst=dst)
        return True

    def _draft_for(self, slot: int, st: _SlotState) -> Optional[np.ndarray]:
        """Ask the drafter for this decoding slot's proposal, capped by
        the slot's adaptive k, the request's remaining token budget, and
        max_seq_len.  Returns None (plain decode span) when speculation
        is off, the caps leave no room, or the drafter has nothing."""
        if self._drafter is None:
            return None
        # page-budget cap: drafts ride the slot's SLACK (held pages +
        # free pool) and never trigger preemption on their own — evicting
        # a neighbour to make room for speculative rows would spend real
        # work on maybe-tokens.  (The plain decode token still preempts
        # under pressure, exactly as without speculation.)
        cache = self.cache
        headroom = ((len(cache._slot_pages[slot]) + cache.free_page_count)
                    * cache.page_size - st.ctx - 1)
        k_cap = min(st.spec_k, self.spec_k,
                    st.req.max_new_tokens - len(st.req.tokens) - 1,
                    self.max_seq_len - st.ctx - 1,
                    headroom)
        if k_cap < 1:
            return None
        history = np.concatenate(
            [st.req.prompt, np.asarray(st.req.tokens, np.int32)])
        draft = np.asarray(self._drafter.propose(history, k_cap),
                           np.int32).reshape(-1)[:k_cap]
        if draft.size == 0:
            return None
        if st.req.eos_id is not None:
            # drafting past a proposed eos is wasted verify rows
            hits = np.flatnonzero(draft == st.req.eos_id)
            if hits.size:
                draft = draft[:int(hits[0]) + 1]
        return draft

    def _ragged_step(self) -> bool:
        """Advance every active slot through ONE unified ragged dispatch:
        decoding slots contribute a 1-token span (or, with speculation
        on, a (1+k)-row VERIFY span carrying the drafter's proposal),
        prefilling slots contribute chunks admitted under the per-step
        token budget."""
        if not self._slots:
            return False
        cache = self.cache
        prof = self.stepprof
        with prof.phase("build_batch"):
            # -- 1. decode/verify spans: draft, then allocate the span's
            # pages
            decode_slots: List[tuple] = []      # (slot, draft-or-None)
            for slot in sorted(self._slots):
                st = self._slots.get(slot)
                if st is None or st.prefilling:
                    continue    # preempted earlier in the pass / chunked
                try:
                    self._fire("draft", slot=slot, pools=cache.pools)
                    draft = self._draft_for(slot, st)
                except Exception as e:  # noqa: BLE001 — a drafting fault
                    # fails THIS request; the batch and engine keep going.
                    # A consume_pools rule is handled HERE: recover the
                    # pools now rather than relying on the dispatch below
                    # to trip on the deleted buffers (a scripted/fake
                    # dispatch never would, and the pools must not stay
                    # silently dead)
                    if slot in self._slots:
                        self._evict(slot, e, "failed")
                    self._recover_pools(e)
                    continue
                n_new = 1 + (0 if draft is None else int(draft.size))
                # the span writes k/v at positions [ctx, ctx+n): allocate
                # them, then copy-on-write the shared page holding ctx
                # (a spliced prefix's partially-filled tail) if any
                if self._alloc_with_preemption(slot, st.ctx + n_new) \
                        and self._make_writable(slot, st):
                    decode_slots.append((slot, draft))
            # -- 2. prefill chunks under the token budget -----------------
            # blocks are the real capacity: each decode span takes
            # ceil(rows / block_q) (1 row, or 1+k for a verify span), each
            # chunk ceil(n / block_q); scheduling in admission order
            blocks_free = self._num_blocks \
                - sum(-(-(1 + (0 if d is None else d.size)) // self.block_q)
                      for s, d in decode_slots if s in self._slots)
            budget = self.prefill_chunk_tokens
            sched: dict[int, int] = {}
            for slot in sorted((s for s in self._slots
                                if self._slots[s].prefilling),
                               key=lambda s: self._slots[s].admit_seq):
                if budget <= 0 or blocks_free <= 0:
                    break
                st = self._slots.get(slot)
                if st is None or not st.prefilling:
                    continue
                remaining = st.pending.size - st.ctx
                n = min(remaining, budget, blocks_free * self.block_q)
                try:
                    with self.tracer.span("prefill", slot=slot, tokens=n,
                                          start=st.ctx):
                        self._fire("prefill", slot=slot, pools=cache.pools)
                        self._fire("prefill_chunk", slot=slot, tokens=n,
                                   start=st.ctx, pools=cache.pools)
                        if not self._alloc_with_preemption(slot,
                                                           st.ctx + n):
                            continue
                        # a spliced slot's first chunk may start inside
                        # the shared tail page: clone it before writing
                        if not self._make_writable(slot, st):
                            continue
                except Exception as e:  # noqa: BLE001 — a per-chunk
                    # injected fault fails THIS request; the rest of the
                    # batch and the engine keep going.  consume_pools is
                    # recovered HERE (see the draft-fault branch) so the
                    # pools never stay silently dead behind a dispatch
                    # that does not read them
                    if slot in self._slots:
                        self._evict(slot, e, "failed")
                    self._recover_pools(e)
                    continue
                sched[slot] = n
                blocks_free -= -(-n // self.block_q)
                budget -= n
            # preemption during scheduling may have evicted earlier spans
            decode_slots = [(s, d) for s, d in decode_slots
                            if s in self._slots]
            sched = {s: n for s, n in sched.items() if s in self._slots}
            if not decode_slots and not sched:
                return True     # allocation alone changed state this pass
            # -- 3. build the fixed-shape ragged batch --------------------
            spans: List[generation.RaggedSpan] = []
            self._batch_spans = []
            self._batch_drafts = {}
            for slot, draft in decode_slots:
                st = self._slots[slot]
                if draft is None:
                    spans.append(generation.RaggedSpan(
                        [st.last_tok], st.ctx + 1,
                        cache._slot_pages[slot]))
                    self._batch_spans.append((slot, "decode", 1))
                else:
                    # verify span: [last_tok] + drafts, logits for EVERY
                    # row (row j scores the target's next token after
                    # draft[:j])
                    rows = 1 + int(draft.size)
                    spans.append(generation.RaggedSpan(
                        np.concatenate([[st.last_tok], draft]),
                        st.ctx + rows, cache._slot_pages[slot],
                        n_out=rows))
                    self._batch_spans.append((slot, "verify", rows))
                    self._batch_drafts[slot] = draft
            for slot, n in sched.items():
                st = self._slots[slot]
                spans.append(generation.RaggedSpan(
                    st.pending[st.ctx:st.ctx + n], st.ctx + n,
                    cache._slot_pages[slot]))
                self._batch_spans.append((slot, "chunk", n))
            batch = generation.build_ragged_batch(
                spans, self._num_blocks, self._num_spans, self.block_q,
                cache.page_size, cache.pages_per_seq,
                num_out=self._num_out)
            self._batch_out = list(zip(batch["out_start"][:len(spans)],
                                       batch["out_len"][:len(spans)]))
        # -- 4. ONE dispatch for the whole mixed batch --------------------
        n_verify = sum(1 for _s, k, _n in self._batch_spans
                       if k == "verify")
        # plain steps (no verify spans) route through the fused
        # single-dispatch executable: sampling happens device-side
        # inside the SAME dispatch and only token ids cross the host
        # boundary.  Verify steps need the full logits block host-side
        # for accept/reject, so they keep the unfused path.  Both paths
        # advance the engine key exactly once per plain step, and the
        # fused kernel's Gumbel-max construction reproduces
        # jax.random.categorical draw-for-draw — so toggling
        # `fused_decode` never changes the emitted token stream.
        use_fused = self.fused_decode and n_verify == 0
        try:
            with self.tracer.span("decode_step", active=len(spans),
                                  decode=len(decode_slots) - n_verify,
                                  verify=n_verify,
                                  chunks=len(sched)) as sp, \
                 prof.phase("dispatch",
                            shape_class=(self._shape_class_fused
                                         if use_fused
                                         else self._shape_class)) as ph:
                self._fire("decode", pools=cache.pools)
                if use_fused:
                    self._fire("fused_decode", pools=cache.pools)
                    toks, k_pool, v_pool = self._ragged_fused(
                        self.params, jnp.asarray(batch["tok"]),
                        jnp.asarray(batch["row_page"]),
                        jnp.asarray(batch["row_off"]),
                        jnp.asarray(batch["row_pos"]),
                        jnp.asarray(batch["block_seq"]),
                        jnp.asarray(batch["block_qpos"]),
                        jnp.asarray(batch["span_len"]),
                        jnp.asarray(batch["ctx_len"]),
                        jnp.asarray(batch["span_pt"]),
                        jnp.asarray(batch["out_rows"]),
                        self._next_key(),
                        cache.pools["k"], cache.pools["v"])
                    logits = None
                    sp.fence(toks)
                    ph.fence(toks)
                else:
                    logits, k_pool, v_pool = self._ragged(
                        self.params, jnp.asarray(batch["tok"]),
                        jnp.asarray(batch["row_page"]),
                        jnp.asarray(batch["row_off"]),
                        jnp.asarray(batch["row_pos"]),
                        jnp.asarray(batch["block_seq"]),
                        jnp.asarray(batch["block_qpos"]),
                        jnp.asarray(batch["span_len"]),
                        jnp.asarray(batch["ctx_len"]),
                        jnp.asarray(batch["span_pt"]),
                        jnp.asarray(batch["out_rows"]),
                        cache.pools["k"], cache.pools["v"])
                    sp.fence(logits)
                    ph.fence(logits)
            cache.pools = {"k": k_pool, "v": v_pool}
            # the verify point wraps the accept/reject pass's input: a
            # fault here (incl. consume_pools on the freshly-swapped
            # pools) fails the step exactly like a dispatch fault
            if n_verify:
                self._fire("verify", pools=cache.pools)
            with self.tracer.span("sample"), prof.phase("sample"):
                self._fire("sample")
                if use_fused:
                    # tokens were sampled inside the dispatch; the
                    # sample phase is just the (num_out,) int32 pull
                    nxt = np.asarray(toks)
                    lg = None
                elif n_verify == 0:
                    # no verify spans this step (speculation off, or the
                    # drafter proposed nothing): sample on device — do
                    # not pull the full (num_out, V) logits block to
                    # host for nothing
                    nxt = np.asarray(self._sample(logits))
                    lg = None
                else:
                    # accept/reject (and sampling for plain spans) runs
                    # host-side over the fixed-shape logits block
                    nxt = None
                    lg = np.asarray(logits)
        except Exception as e:  # noqa: BLE001 — dispatch/sampling fault:
            # the donated pools may be consumed and this step's KV writes
            # are suspect.  Fail every in-flight request, recover the
            # pools, keep serving the queue.
            self._fail_inflight(e)
            return True
        n_prefill_tokens = sum(sched.values())
        n_verify_rows = sum(n for _s, _k, n in self._batch_spans
                            if _k == "verify")
        batch_tokens = (len(decode_slots) - n_verify + n_verify_rows
                        + n_prefill_tokens)
        with self._cv:
            # verify_tokens lands in the SAME locked block as
            # ragged_batch_tokens so check_invariants' ragged identity
            # (ragged == decode + prefill + verify) cannot tear against
            # a concurrent step thread; the per-verdict counters follow
            # in _commit_verify, so the row-vs-verdict identity is only
            # decidable at quiescence (the checker gates it there)
            if decode_slots:
                self.stats["decode_steps"] += 1
                self.stats["decode_tokens"] += len(decode_slots) - n_verify
            if use_fused:
                self.stats["fused_decode_steps"] += 1
            if n_verify:
                self.stats["verify_tokens"] += n_verify_rows
            if sched:
                self.stats["prefill_chunks"] += len(sched)
                self.stats["prefill_tokens"] += n_prefill_tokens
            self.stats["ragged_batch_tokens"] += batch_tokens
        self._last_batch_tokens = batch_tokens
        # -- 5. post-process each span's outcome --------------------------
        now = time.monotonic()
        with prof.phase("commit"):
            for i, (slot, kind, n) in enumerate(self._batch_spans):
                st = self._slots.get(slot)
                if st is None:
                    continue
                o0, on = self._batch_out[i]
                if kind == "verify":
                    self._commit_verify(slot, st, lg[o0:o0 + on],
                                        self._batch_drafts[slot], now)
                    continue
                if kind == "chunk":
                    st.ctx += n
                    self._rq_event(st.req, "prefill_chunk", tokens=n,
                                   ctx=st.ctx)
                    if st.prefilling:
                        continue        # more chunks on later steps
                    # prefill finished: its pages become cached prefix —
                    # the index takes refs so the KV survives this slot
                    # and later admissions splice instead of re-prefilling
                    self._register_prefix(slot, st)
                    if self.role == "prefill" and st.req.allow_handoff \
                            and st.sample_on_finish:
                        # disaggregated serving: resolve here with the
                        # KV staged for a decode replica — no token is
                        # sampled on this class (the decode side owns
                        # the whole sampling chain, so the handed-off
                        # stream is token-exact vs a mixed engine)
                        self._rq_event(st.req, "prefill_done",
                                       ctx=st.ctx)
                        self._handoff_slot(slot, st)
                        continue
                    if not st.sample_on_finish:
                        # recompute-resume: its next token was sampled
                        # before the preemption; decode continues with
                        # last_tok
                        st.pending = None
                        continue
                    st.pending = None
                    tok = self._row_token(nxt, lg, o0)
                    self._rq_event(st.req, "prefill_done", ctx=st.ctx)
                else:
                    st.ctx += 1
                    tok = self._row_token(nxt, lg, o0)
                    self._rq_event(st.req, "decode", ctx=st.ctx)
                st.last_tok = tok
                self._emit_tokens(slot, st, [tok], now)
        return True

    def _row_token(self, nxt, lg, row: int) -> int:
        """Next token for a plain (non-verify) span's logits row: the
        device-sampled array when speculation is off, host sampling off
        the pulled logits block otherwise."""
        if nxt is not None:
            return int(nxt[row])
        if self.temperature == 0.0:
            return int(np.argmax(lg[row]))
        p = generation.filtered_probs(lg[row:row + 1], self.temperature,
                                      self.top_k, self.top_p)[0]
        return int(self._spec_rng.choice(p.size, p=p / p.sum()))

    def _commit_verify(self, slot: int, st: _SlotState, rows, draft,
                       now: float) -> None:
        """Accept/reject one verify span and commit the outcome: emit the
        accepted drafts + the correction/bonus token, advance ctx past
        the ACCEPTED tokens only, and roll back the rejected tail (pure
        length bookkeeping + trailing-page release — the kernel's
        ctx_len masking never reads past the sequence length, and the
        next span overwrites the stale rows in place)."""
        k = int(draft.size)
        with self.stepprof.phase("verify"):
            if self.temperature == 0.0:
                emitted, m = generation.verify_greedy(rows, draft)
            else:
                probs = generation.filtered_probs(
                    rows, self.temperature, self.top_k, self.top_p)
                emitted, m = generation.verify_rejection(
                    probs, draft, self._spec_rng)
        # adaptive k: grow on full acceptance, shrink on a bad span
        if m == k:
            st.spec_k = min(st.spec_k + 1, self.spec_k)
        elif 2 * m < k:
            st.spec_k = max(1, st.spec_k - 1)
        # commit: last_tok + the m accepted drafts are now real cache
        # content; the k - m rejected rows are logically retired
        st.ctx += 1 + m
        freed = self.cache.truncate_slot(slot, st.ctx)
        if freed:
            self.tracer.instant("spec_rollback", slot=slot, pages=freed)
        st.last_tok = emitted[-1]
        finished, n_emitted = self._emit_tokens(slot, st, emitted, now)
        self._rq_event(st.req, "verify", drafted=k, accepted=m,
                       emitted=n_emitted, ctx=st.ctx)
        with self._cv:
            self.stats["spec_steps"] += 1
            self.stats["spec_drafted"] += k
            self.stats["spec_accepted"] += m
            self.stats["spec_rejected"] += k - m
            self.stats["spec_bonus"] += 1
            self.stats["spec_emitted"] += n_emitted
        self._h_accept.observe(m / k if k else 1.0)

    def _emit_tokens(self, slot: int, st: _SlotState, toks, now: float
                     ) -> tuple:
        """Append tokens to the request (same timestamp: they arrived in
        one step), finishing at eos/max_new_tokens — any remaining
        tokens are dropped.  Returns (finished, n_appended)."""
        tstats = self._tenant_state(st.req.tenant)
        for j, tok in enumerate(toks):
            st.req.tokens.append(int(tok))
            if st.req.t_first_token is None:
                st.req.t_first_token = now
                self._h_ttft.observe(now - st.req.t_submit)
                self.slo.observe("ttft", now - st.req.t_submit, t=now)
                self._tenant_slo_observe(st.req.tenant, "ttft",
                                         now - st.req.t_submit, t=now)
            elif st.req.t_last_token is not None:
                self._h_itl.observe(now - st.req.t_last_token)
                self.slo.observe("inter_token",
                                 now - st.req.t_last_token, t=now)
                self._tenant_slo_observe(st.req.tenant, "inter_token",
                                         now - st.req.t_last_token, t=now)
                # only the FIRST gap of a multi-token span feeds the
                # watchdog: the rest share `now` and their 0.0 gaps
                # would drive the ITL baseline median to zero,
                # permanently disarming spike detection on exactly the
                # speculating engines it watches
                if j == 0:
                    self.watchdog.observe_itl(now - st.req.t_last_token)
            st.req.t_last_token = now
            if (st.req.eos_id is not None and tok == st.req.eos_id) \
                    or len(st.req.tokens) >= st.req.max_new_tokens:
                # the tagged/untagged emission counters move together so
                # the per-tenant identity (sum of tenant emitted ==
                # llm_emitted_tokens) holds at every quiescent point
                self.stats.inc("emitted_tokens", j + 1)
                tstats.inc("emitted_tokens", j + 1)
                del self._slots[slot]
                self._finish(slot, st.req)
                return True, j + 1
        self.stats.inc("emitted_tokens", len(toks))
        tstats.inc("emitted_tokens", len(toks))
        return False, len(toks)

    def _fail_inflight(self, e: BaseException) -> None:
        for slot in list(self._slots):
            self._evict(slot, e, "failed")
        self._recover_pools(e)

    def _finish(self, slot: int, req: _Request):
        idx = self.prefix_index
        if idx is not None:
            # the prompt's partial tail page is shareable NOW: the slot
            # is done appending, so the index can reference it without
            # ever forcing a copy on the request that computed it (a
            # later splicer copy-on-writes its own private clone)
            pages = self.cache._slot_pages[slot]
            need = self.cache.pages_needed(req.prompt.size)
            if 0 < need <= len(pages):
                idx.insert(req.prompt, req.prompt.size, pages[:need],
                           tier=req.priority)
        self.cache.release_slot(slot)
        with self._cv:
            self.stats["completed"] += 1
            self._tenant_state(req.tenant).inc("completed")
        if req.t_admit is not None and req.tokens:
            dur = time.monotonic() - req.t_admit
            if dur > 0:
                self._h_tps.observe(len(req.tokens) / dur)
        self._rq_event(req, "resolve", outcome="completed",
                       tokens=len(req.tokens))
        req._resolve()


def serve_llm(engine: LLMEngine, host: str = "127.0.0.1", port: int = 0,
              max_body_bytes: int = 8 * 1024 * 1024,
              request_timeout: float = 300.0):
    """HTTP JSON generation endpoint over a continuous-batching engine.

    POST / with {"prompt": [token ids], "max_new_tokens": N,
    "eos_id": optional, "deadline": optional seconds, "request_id":
    optional trace id} returns {"tokens": [...], "request_id": "..."}.
    The request id keys the request's obs timeline: `GET
    /debug/request/<id>` returns the queryable lifecycle (submit ->
    admit -> prefill chunks -> decode/verify steps -> preempt/resume ->
    resolve) from the engine's RequestRegistry, 404 once evicted from
    the LRU window.  Concurrent requests share the engine's decode
    batch (continuous batching), so throughput scales with occupancy,
    not request count.

    Failure surface: a full pending queue replies 503 with a Retry-After
    header; a request that misses `request_timeout` replies 504 AND is
    cancelled so its slot/pages free immediately (it must not starve the
    batch until max_new_tokens); GET /healthz replies 200 only while the
    engine's step thread is alive; GET /stats returns a locked snapshot
    of the engine counters (Content-Type: application/json); GET /metrics
    renders the same registry as Prometheus text exposition format
    (Content-Type: text/plain; version=0.0.4) with the TTFT /
    inter-token / queue-wait histograms.  Returns (server, thread);
    server.shutdown() stops the HTTP loop AND the engine."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    engine.start()

    class Handler(BaseHTTPRequestHandler):
        def _reply_text(self, status: int, text: str, content_type: str,
                        headers=None):
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply(self, status: int, payload: dict, headers=None):
            self._reply_text(status, json.dumps(payload),
                             "application/json", headers)

        def do_GET(self):
            path = self.path.rstrip("/")
            if path == "/stats":
                self._reply(200, engine.stats_snapshot())
            elif path.startswith("/debug/request/"):
                rid = path.rsplit("/", 1)[1]
                reg = getattr(engine, "reqtrace", None)
                tl = None if reg is None else reg.to_dict(rid)
                if tl is None:
                    self._reply(404, {"error": f"unknown request id "
                                               f"{rid!r} (never traced, "
                                               "or evicted)"})
                else:
                    self._reply(200, tl)
            elif path == "/metrics":
                reg = getattr(engine, "metrics", None)
                if reg is None:
                    self._reply(404, {"error": "engine has no metrics "
                                               "registry"})
                    return
                self._reply_text(200, reg.render(),
                                 "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                t = engine._thread
                alive = (t is not None and t.is_alive()
                         and not engine._stop)
                self._reply(200 if alive else 503,
                            {"ok": alive,
                             "step_thread_alive": bool(t and t.is_alive()),
                             "stopped": engine._stop})
            else:
                self._reply(404, {"error": "unknown path"})

        # the POST contract is a closed schema: an unrecognized field is
        # a 400 with a typed error, not a silent drop — a client that
        # misspells "tenant" must not silently run as the default tenant
        _POST_FIELDS = frozenset((
            "prompt", "max_new_tokens", "eos_id", "deadline",
            "request_id", "tenant", "priority"))

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", "0"))
                if n > max_body_bytes:
                    self._reply(413, {"error": "body too large"})
                    return
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        self._reply(400, {
                            "error": "bad_body",
                            "detail": "request body must be a JSON "
                                      "object"})
                        return
                    unknown = sorted(set(req) - self._POST_FIELDS)
                    if unknown:
                        self._reply(400, {
                            "error": "unknown_field",
                            "fields": unknown,
                            "detail": f"unrecognized field(s) "
                                      f"{unknown}; allowed: "
                                      f"{sorted(self._POST_FIELDS)}"})
                        return
                    prompt = req["prompt"]
                    max_new = int(req.get("max_new_tokens", 16))
                    eos_id = req.get("eos_id")
                    deadline = req.get("deadline")
                    req_id = req.get("request_id")
                    if req_id is not None:
                        req_id = str(req_id)
                    tenant = req.get("tenant")
                    priority = req.get("priority")
                except (json.JSONDecodeError, KeyError, TypeError,
                        ValueError) as e:
                    self._reply(400, {"error": "bad_body",
                                      "detail": f"bad request body: "
                                                f"{e!r}"})
                    return
                try:
                    handle = engine.submit(prompt, max_new, eos_id,
                                           deadline=deadline,
                                           req_id=req_id,
                                           tenant=tenant,
                                           priority=priority)
                except QueueFull as e:
                    retry = max(1, int(-(-e.retry_after // 1)))
                    self._reply(503, {"error": str(e)},
                                headers={"Retry-After": str(retry)})
                    return
                except _qos.UnknownTenant as e:
                    self._reply(400, {"error": "unknown_tenant",
                                      "tenant": e.tenant,
                                      "detail": str(e)})
                    return
                except (ValueError, RuntimeError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                try:
                    toks = handle.result(timeout=request_timeout)
                except TimeoutError as e:
                    # covers both the wait timeout and an engine-side
                    # DeadlineExceeded; cancel so the slot/pages free NOW
                    handle.cancel()
                    self._reply(504, {"error": f"generation timed out: {e}"})
                    return
                except RequestCancelled as e:
                    self._reply(409, {"error": str(e)})
                    return
                # the RESOLVED labels echo back (tenant defaulting and
                # priority clamping happened in submit), matching the
                # submit event on the request's /debug timeline
                self._reply(200, {"tokens": toks,
                                  "request_id": handle.req_id,
                                  "tenant": handle.tenant,
                                  "priority": handle.priority})
            except Exception as e:  # noqa: BLE001 — server-side fault
                self._reply(500, {"error": repr(e)})

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    _orig_shutdown = srv.shutdown

    def _shutdown():
        _orig_shutdown()
        engine.shutdown()

    srv.shutdown = _shutdown
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t
