"""Continuous-batching LLM serving engine over the paged KV cache.

The reference serves generation through a one-request-at-a-time predictor
loop (PaddleNLP over analysis_predictor.h:94).  Production TPU serving
(the Gemma-on-TPU study, arxiv 2605.25645; Ragged Paged Attention, arxiv
2604.15464) gets its throughput from *continuous batching* and its memory
efficiency from *admitting on demand and preempting under pressure*
instead of reserving worst-case pages up front.

Engine anatomy:
  * `PagedKVCache` (models/generation.py) — page pools + page tables;
    each admitted request owns a decode slot and that slot's pages.
  * admission — pending requests enter free slots mid-flight; only the
    PROMPT's pages are reserved (admit-on-demand).  The prompt is
    prefilled through the dense flash path (bucketed to the next
    power-of-two length) and scattered into the slot's pages.
  * decode — ONE jitted step advances every active slot through the
    Pallas paged-attention kernel; empty slots point at the reserved
    scratch page and their logits are ignored.  The incoming token's page
    is allocated on demand, and may FAIL under pressure.
  * preemption — when mid-decode allocation fails, a victim is picked
    (`victim_policy`: "latest" admitted, or "fewest_tokens" generated),
    its pages are released, and the request re-enters the HEAD of the
    pending deque carrying either a host copy of its KV pages
    (`preempt_mode="swap"`: gather at preempt, scatter back on resume) or
    nothing (`preempt_mode="recompute"`: prompt + generated-so-far is
    re-prefilled through the same bucketed prefill path on resume).  The
    LAST runnable sequence is never preempted — and a single request's
    worst case is validated against the pool at submit() — so forward
    progress is deadlock-free.
  * eviction — on EOS / max_new_tokens / cancel() / deadline expiry the
    slot's pages return to the free pool and the slot re-enters admission.

Request lifecycle: `submit()` returns a handle with `result()`, `done()`
and `cancel()`; per-request deadlines are enforced at every step()
boundary (queued or mid-decode -> `DeadlineExceeded`); the pending queue
is bounded (`max_pending`) and overflow raises a typed `QueueFull`
(HTTP 503 + Retry-After in serve_llm).  `serve_llm` maps a `result()`
timeout to HTTP 504 AND cancels the request so its slot/pages free
immediately instead of starving the batch until max_new_tokens.

Every failure path is exercised by the fault-injection harness in
`paddle_tpu.inference.faults`: the engine calls `faults.fire(point, ...)`
at named injection points (prefill / decode / page_alloc / sample /
swap_out / swap_in) and the harness's invariant checker proves no pages,
slots or handles leak under any schedule.

Telemetry (paddle_tpu.obs): every lifecycle counter lives in a metrics
Registry (`engine.metrics`) — `stats_snapshot()` (the /stats JSON) and
`GET /metrics` (Prometheus text) read the SAME storage, so the two
surfaces cannot drift.  Per-request latency metrics are derived from
lifecycle timestamps: queue wait (submit -> admission), TTFT (submit ->
first token), inter-token gaps, and tokens/sec.  The step loop is span-
instrumented (admit / prefill / decode_step / sample / preempt /
swap_out / swap_in) against `engine.tracer` — a no-op single branch
until the tracer is enabled, with `block_until_ready` fencing on the
dispatch results so spans time the compute, not the enqueue.
"""

from __future__ import annotations

import collections
import collections.abc
import functools
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..models import generation
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

__all__ = ["LLMEngine", "serve_llm", "QueueFull", "RequestCancelled",
           "DeadlineExceeded", "EngineStopped"]


class EngineStopped(RuntimeError):
    """submit() refused: the engine is shut down OR its step thread died.
    Typed and immediate — enqueueing into a dead loop would hand back a
    handle no thread will ever resolve, so result() would hang forever.
    The fleet Router treats this as replica death (eject + place
    elsewhere); serve_fleet maps it to HTTP 503."""


class QueueFull(RuntimeError):
    """submit() refused: the bounded pending queue is at capacity.
    serve_llm maps this to HTTP 503 with a Retry-After header."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = float(retry_after)


class RequestCancelled(RuntimeError):
    """The request was cancelled before it finished."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before it finished."""


class _ResumeState:
    """What a preempted request needs to re-enter a slot: decode position,
    the sampled-but-not-yet-cached token, how many pages it held, and (swap
    mode only) host copies of those pages' KV."""

    __slots__ = ("ctx", "last_tok", "n_pages", "host_k", "host_v")

    def __init__(self, ctx: int, last_tok: int, n_pages: int,
                 host_k=None, host_v=None):
        self.ctx = ctx
        self.last_tok = last_tok
        self.n_pages = n_pages
        self.host_k = host_k
        self.host_v = host_v


class _Request:
    """One queued/in-flight generation request."""

    def __init__(self, prompt, max_new_tokens: int, eos_id: Optional[int],
                 deadline: Optional[float] = None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_id = eos_id
        self.deadline = (None if deadline is None
                         else time.monotonic() + float(deadline))
        # lifecycle timestamps (monotonic): the per-request latency
        # metrics — queue wait, TTFT, inter-token — derive from these
        self.t_submit = time.monotonic()
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.cancelled = False
        self.resolutions = 0        # invariant: exactly 1 once done()
        self._resume: Optional[_ResumeState] = None
        self._engine: Optional["LLMEngine"] = None
        self._event = threading.Event()
        # fired once, on the FIRST resolution (routers hook completion
        # here instead of polling done()); exceptions are swallowed — a
        # broken observer must not wedge the step thread
        self._callbacks: List = []

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; returns the generated tokens
        (ending at eos_id when one was hit)."""
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        """Cancel the request: a queued one resolves immediately with
        RequestCancelled; an in-flight one is evicted (pages released) at
        the next step() boundary.  No-op once done."""
        eng = self._engine
        if eng is None:
            self.cancelled = True
            return
        with eng._cv:
            if self.done():
                return
            self.cancelled = True
            try:
                eng._pending.remove(self)
            except ValueError:
                eng._cv.notify_all()   # in flight: wake the loop to evict
                return
            eng.stats["cancelled"] += 1
            self._resolve(RequestCancelled("request cancelled"))

    def _resolve(self, error: Optional[BaseException] = None) -> None:
        # counts EVERY call, even redundant ones, so the invariant checker
        # can prove each handle resolved exactly once
        self.resolutions += 1
        if self._event.is_set():
            return
        self.error = error
        self._event.set()
        for cb in list(self._callbacks):
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — observer bug stays local
                pass


class _SlotState:
    def __init__(self, req: _Request, last_tok: int, ctx: int,
                 admit_seq: int):
        self.req = req
        self.last_tok = last_tok    # sampled, not yet in the cache
        self.ctx = ctx              # tokens currently cached
        self.admit_seq = admit_seq  # admission order (victim policy)


class _StatsDict(collections.abc.MutableMapping):
    """The engine's legacy counter dict, backed by registry Counters.

    Call sites keep writing `stats["completed"] += 1`; each key is ONE
    `<prefix>_<key>_total` Counter in the metrics registry, so /stats
    JSON and /metrics Prometheus text read identical storage and cannot
    drift.  (Keys already ending in `_total` keep their name:
    "steps_total" -> `llm_steps_total`.)  The Router reuses this with
    prefix="fleet" for its own counters."""

    _HELP = {
        "accepted": "requests accepted by submit() (queued or better)",
        "admitted": "fresh admissions prefillled into a slot",
        "completed": "requests finished with tokens",
        "decode_steps": "batched decode dispatches",
        "decode_tokens": "tokens produced by decode dispatches",
        "preemptions": "victims evicted under page pressure",
        "swapped_in": "preempted requests resumed via host-KV scatter",
        "resumed": "preempted requests re-admitted (either mode)",
        "cancelled": "requests resolved by cancellation",
        "timed_out": "requests resolved by deadline expiry",
        "failed": "requests resolved with an engine/dispatch error",
        "steps_total": "engine step() iterations",
    }

    def __init__(self, registry: obs_metrics.Registry,
                 keys: Sequence[str], prefix: str = "llm",
                 help: Optional[dict] = None):
        self._registry = registry
        self._prefix = prefix
        self._help = dict(self._HELP) if help is None else dict(help)
        self._counters = {}
        for k in keys:
            self._counters[k] = self._make(k)

    def _make(self, key: str) -> obs_metrics.Counter:
        name = (f"{self._prefix}_{key}" if key.endswith("_total")
                else f"{self._prefix}_{key}_total")
        return self._registry.counter(name, self._help.get(key, ""))

    def __getitem__(self, key: str) -> int:
        return int(self._counters[key].value)

    def __setitem__(self, key: str, value) -> None:
        if key not in self._counters:
            self._counters[key] = self._make(key)
        self._counters[key].set(value)

    def inc(self, key: str, n: int = 1) -> None:
        """Atomic increment (Counter.inc holds the metric's lock).
        `stats[k] += 1` is a separate read then absolute write — fine
        under the engine's _cv, but the Router bumps counters from HTTP
        handler, engine step, and health-tick threads concurrently,
        where the read-modify-write loses counts."""
        if key not in self._counters:
            self._counters[key] = self._make(key)
        self._counters[key].inc(n)

    def __delitem__(self, key: str) -> None:
        raise TypeError("engine stats counters cannot be removed")

    def __iter__(self):
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)


def default_prefill_buckets(max_seq_len: int, rope_len: int,
                            lo: int = 8) -> List[int]:
    """The engine's default prefill compile menu: powers of two from `lo`
    up to max_seq_len, the top bucket clamped to the rope table (a
    non-power-of-2 max_position_embeddings would otherwise over-slice
    it).  Every distinct bucket is one compiled prefill executable."""
    menu, b = [], lo
    while True:
        menu.append(min(b, rope_len))
        if b >= max_seq_len:
            break
        b *= 2
    return sorted(set(menu))


class LLMEngine:
    """Continuous-batching generation engine (queue -> slots -> tokens).

    `num_slots` is the decode batch width (one compiled decode program);
    `num_pages` bounds resident KV memory — when smaller than worst-case
    num_slots occupancy the engine admits on demand and PREEMPTS under
    pressure (see module docstring), so a pool sized for the *expected*
    footprint still serves the worst case correctly, just slower.

    preempt_mode: "swap" (KV pages copied to host at preempt, scattered
    back on resume) or "recompute" (prompt+generated re-prefilled on
    resume).  victim_policy: "latest" (latest-admitted) or "fewest_tokens"
    (least work lost).  max_pending bounds the queue (QueueFull beyond).
    faults: an optional paddle_tpu.inference.faults.FaultInjector.
    tracer: a paddle_tpu.obs.Tracer (default: the process-wide tracer,
    disabled until enabled — instrumentation is then a no-op branch).
    metrics: a paddle_tpu.obs.Registry (default: a fresh per-engine
    registry; serve_llm's GET /metrics renders it).

    prefill_buckets: the prefill COMPILE MENU — every prompt (and every
    recompute-resume) right-pads to the smallest bucket >= its length,
    so each distinct bucket is exactly one compiled prefill executable.
    Default: powers of two up to max_seq_len (top clamped to the rope
    table).  expected_prompt_lens: an optional workload sample; when
    given, the menu is LINTED at construction (analysis.lint_bucket_menu)
    and lengths straddling a bucket edge raise a RECOMPILE_BUCKET_MISS
    warning carrying the suggested menu edit (`engine.bucket_report`
    holds the full report; `prefill_probe_args()` feeds the same menu to
    the Graph Doctor's shape-poly probe).
    """

    def __init__(self, params, config, num_slots: int = 4,
                 page_size: int = 16, max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 max_pending: Optional[int] = None,
                 preempt_mode: str = "swap",
                 victim_policy: str = "latest",
                 faults=None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 expected_prompt_lens: Optional[Sequence[int]] = None,
                 tracer: Optional[obs_trace.Tracer] = None,
                 metrics: Optional[obs_metrics.Registry] = None):
        self.params = params
        self.config = config
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        self.max_seq_len = int(max_seq_len or config.max_position_embeddings)
        if self.max_seq_len > config.max_position_embeddings:
            # past the rope table jnp.take would silently clamp positions —
            # wrong tokens with no diagnostic
            raise ValueError(
                f"max_seq_len={self.max_seq_len} exceeds the model's "
                f"max_position_embeddings={config.max_position_embeddings}")
        if preempt_mode not in ("swap", "recompute"):
            raise ValueError(f"unknown preempt_mode {preempt_mode!r}")
        if victim_policy not in ("latest", "fewest_tokens"):
            raise ValueError(f"unknown victim_policy {victim_policy!r}")
        self.preempt_mode = preempt_mode
        self.victim_policy = victim_policy
        self.max_pending = None if max_pending is None else int(max_pending)
        self.faults = faults
        rope_len = config.max_position_embeddings
        if prefill_buckets is None:
            self.prefill_buckets = default_prefill_buckets(
                self.max_seq_len, rope_len)
        else:
            self.prefill_buckets = sorted({int(b) for b in prefill_buckets})
            if not self.prefill_buckets:
                raise ValueError("prefill_buckets must not be empty")
            if self.prefill_buckets[-1] < self.max_seq_len:
                raise ValueError(
                    f"largest prefill bucket {self.prefill_buckets[-1]} < "
                    f"max_seq_len={self.max_seq_len}: a worst-case resume "
                    "could not re-prefill")
            if self.prefill_buckets[-1] > rope_len:
                raise ValueError(
                    f"prefill bucket {self.prefill_buckets[-1]} exceeds the "
                    f"rope table (max_position_embeddings={rope_len})")
        self.bucket_report = None
        if expected_prompt_lens is not None:
            from .. import analysis

            self.bucket_report = analysis.lint_bucket_menu(
                self.prefill_buckets, expected_prompt_lens,
                options={"bucket_align": max(4, int(page_size))})
            for f in self.bucket_report:
                if f.severity >= analysis.Severity.WARNING:
                    import warnings

                    warnings.warn(f"LLMEngine bucket menu: {f}",
                                  stacklevel=2)
        pages_per_seq = -(-self.max_seq_len // page_size)
        if num_pages is None:
            num_pages = 1 + num_slots * pages_per_seq   # full provisioning
        self.cache = generation.PagedKVCache(
            config, num_pages=num_pages, page_size=page_size,
            max_slots=num_slots, pages_per_seq=pages_per_seq)
        self._pending: collections.deque = collections.deque()
        self._slots: dict[int, _SlotState] = {}
        self._admit_seq = 0
        self._key = jax.random.PRNGKey(seed)
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._t_start = time.monotonic()
        self.tracer = tracer if tracer is not None else obs_trace.get_tracer()
        self.metrics = metrics if metrics is not None \
            else obs_metrics.Registry()
        if self.metrics.get("llm_accepted_total") is not None:
            # a shared registry would silently merge both engines'
            # counters and rebind the state gauges to the last engine —
            # corrupted numbers, no error.  Fail fast instead: one
            # registry per engine; a router aggregates per-replica
            # renders, it does not pool storage.
            raise ValueError(
                "metrics registry already serves another LLMEngine; "
                "give each engine its own Registry")
        self.stats = _StatsDict(self.metrics, (
            "accepted", "admitted", "completed", "decode_steps",
            "decode_tokens", "preemptions", "swapped_in", "resumed",
            "cancelled", "timed_out", "failed", "steps_total"))
        reg = self.metrics
        self._h_queue_wait = reg.histogram(
            "llm_queue_wait_seconds", "submit() -> slot admission")
        self._h_ttft = reg.histogram(
            "llm_ttft_seconds", "submit() -> first generated token")
        self._h_itl = reg.histogram(
            "llm_inter_token_seconds",
            "gap between consecutive tokens of one request (includes "
            "preemption/requeue time: the latency the CLIENT sees)")
        self._h_tps = reg.histogram(
            "llm_request_tokens_per_sec",
            "per completed request: tokens / (finish - admission)",
            buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000,
                     5000, 10000))
        # gauges read engine state lazily at render/snapshot time (the
        # slot/page structures are owned lock-free by the step thread, so
        # a gauge can be one step fresher than the counters next to it)
        reg.gauge("llm_queue_depth", "pending requests").set_function(
            lambda: len(self._pending))
        reg.gauge("llm_slots_in_flight", "occupied decode slots"
                  ).set_function(lambda: len(self._slots))
        reg.gauge("llm_free_pages", "KV pages in the free pool"
                  ).set_function(lambda: self.cache.free_page_count)
        reg.gauge("llm_free_slots", "free decode slots").set_function(
            lambda: self.cache.free_slot_count)
        reg.gauge("llm_uptime_seconds", "seconds since engine construction"
                  ).set_function(lambda: time.monotonic() - self._t_start)

        cfg = config

        # pools are DONATED: the caller always replaces cache.pools with the
        # result, so XLA updates the page pool in place instead of copying
        # the whole (L, P, ps, Hkv, D) cache every token (donation is a
        # no-op on CPU, where jax ignores it with a one-time warning)
        @functools.partial(jax.jit, donate_argnums=(4, 5))
        def _decode(params, tok, ctx, page_table, k_pool, v_pool):
            return generation.forward_paged_decode(
                params, tok, cfg, {"k": k_pool, "v": v_pool},
                page_table, ctx)

        self._decode = _decode

        @functools.partial(jax.jit, donate_argnums=(2, 3))
        def _prefill(params, ids, k_pool, v_pool, pt_row, true_len):
            # ids: (1, Sb) RIGHT-padded to the bucket; causal attention
            # keeps positions < true_len independent of the padding, and
            # padded positions scatter into the scratch page
            dense = generation.init_kv_cache(cfg, 1, ids.shape[1])
            logits, dense = generation.forward_with_cache(
                params, ids, cfg, dense, 0)
            pools = generation.scatter_prefill_into_pages(
                dense, {"k": k_pool, "v": v_pool}, pt_row, ids.shape[1],
                true_len=true_len[None])
            last = jnp.take_along_axis(
                logits, jnp.reshape(true_len - 1, (1, 1, 1)), axis=1)[:, 0]
            return last, pools["k"], pools["v"]

        self._prefill = _prefill

        # swap path: page gather (preempt) reads the pools — NOT donated;
        # page scatter (resume) replaces them — donated like decode.  idx
        # is padded to a fixed pages_per_seq with the reserved page 0, so
        # one compiled program covers every page count
        @jax.jit
        def _swap_out(k_pool, v_pool, idx):
            out = generation.gather_kv_pages(
                {"k": k_pool, "v": v_pool}, idx)
            return out["k"], out["v"]

        self._swap_out = _swap_out

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _swap_in(k_pool, v_pool, idx, host_k, host_v):
            pools = generation.scatter_kv_pages(
                {"k": k_pool, "v": v_pool}, idx,
                {"k": host_k, "v": host_v})
            return pools["k"], pools["v"]

        self._swap_in = _swap_in

    def _bucket_for(self, n: int) -> int:
        """Smallest menu bucket >= n (exists: the menu covers
        max_seq_len, and submit() validates n <= max_seq_len)."""
        for b in self.prefill_buckets:
            if b >= n:
                return b
        return self.prefill_buckets[-1]

    def prefill_probe_args(self) -> List[tuple]:
        """One abstract `_prefill` arg tuple per menu bucket — the Graph
        Doctor's shape-poly probe: `analysis.analyze(engine._prefill,
        *args[0], probe_args=args[1:], options={"expected_signatures":
        len(engine.prefill_buckets)})` passes while the menu's compiles
        are the ONLY distinct signatures.  The gate is COUNT-based: to
        lint real traffic, probe the real call sites TOGETHER with this
        full menu (any signature outside the menu then exceeds the
        expected count and fires RECOMPILE_SHAPE_POLY)."""
        pools = self.cache.pools
        out = []
        for b in self.prefill_buckets:
            out.append((
                self.params,
                jax.ShapeDtypeStruct((1, b), jnp.int32),
                jax.ShapeDtypeStruct(pools["k"].shape, pools["k"].dtype),
                jax.ShapeDtypeStruct(pools["v"].shape, pools["v"].dtype),
                jax.ShapeDtypeStruct((1, self.cache.pages_per_seq),
                                     jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
            ))
        return out

    # -- client surface -----------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline: Optional[float] = None) -> _Request:
        """Queue a request.  deadline: seconds from now; once expired the
        request resolves with DeadlineExceeded at the next step() boundary,
        whether still queued or mid-decode.  Raises QueueFull when the
        bounded pending queue is at capacity."""
        req = _Request(prompt, max_new_tokens, eos_id, deadline=deadline)
        total = req.prompt.size + req.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens={total} exceeds engine "
                f"max_seq_len={self.max_seq_len}")
        if self.cache.pages_needed(total) > self.cache.num_pages - 1:
            # the preemption guarantee rests on this: a LONE sequence must
            # always be able to grow to its worst case
            raise ValueError(
                f"request needs {self.cache.pages_needed(total)} pages but "
                f"the pool only holds {self.cache.num_pages - 1}")
        with self._cv:
            if self._stop:
                raise EngineStopped("engine is stopped")
            t = self._thread
            if t is not None and not t.is_alive():
                # the step thread CRASHED (it exits cleanly only via
                # _stop, handled above): enqueueing would hand back a
                # handle nothing will ever resolve
                raise EngineStopped(
                    "engine step thread died; the engine is stopped "
                    "until a supervisor rebuilds it")
            if (self.max_pending is not None
                    and len(self._pending) >= self.max_pending):
                raise QueueFull(
                    f"pending queue is full ({self.max_pending} requests)",
                    retry_after=1.0)
            req._engine = self
            self._pending.append(req)
            # every accepted request ends in EXACTLY one terminal counter
            # (completed/cancelled/timed_out/failed) — the registry
            # identity faults.check_invariants asserts
            self.stats["accepted"] += 1
            self._cv.notify()
        return req

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> List[List[int]]:
        """Synchronous convenience: submit all prompts and wait.  With the
        background loop running (start()/serve_llm) this only waits — the
        loop thread owns the cache; driving step() from a second thread
        would race slot/page allocation."""
        reqs = [self.submit(p, max_new_tokens, eos_id) for p in prompts]
        if self._thread is None:
            while not all(r.done() for r in reqs):
                if not self.step():
                    break  # no progress possible (errors already recorded)
            timeout = 0
        return [r.result(timeout=timeout) for r in reqs]

    def stats_snapshot(self) -> dict:
        """SOURCE OF TRUTH for engine counters: a copy taken under
        self._cv (every counter write holds the lock, so no torn
        multi-counter updates) plus queue/pool gauges, `uptime_s`, and
        `steps_total`.  The counters are read from the metrics registry
        — the same storage `GET /metrics` renders, so the JSON and
        Prometheus surfaces cannot drift.  The gauges are instantaneous
        reads: slot/page state is owned lock-free by the step thread, so
        a gauge can be one step fresher than the counters next to it."""
        with self._cv:
            snap = dict(self.stats)
            snap["queue_depth"] = len(self._pending)
            snap["free_pages"] = self.cache.free_page_count
            snap["free_slots"] = self.cache.free_slot_count
            snap["uptime_s"] = time.monotonic() - self._t_start
        return snap

    def latency_snapshot(self) -> dict:
        """Per-request latency percentiles over the recent raw-sample
        window (exact, not bucket-interpolated): {"ttft_s",
        "inter_token_s", "queue_wait_s", "tokens_per_sec"} each carrying
        {p50, p99, n}.  The public face of the lifecycle histograms —
        bench.py and routers consume this, not the private fields."""
        out = {}
        for key, hist in (("ttft_s", self._h_ttft),
                          ("inter_token_s", self._h_itl),
                          ("queue_wait_s", self._h_queue_wait),
                          ("tokens_per_sec", self._h_tps)):
            samples = hist.samples()
            out[key] = {"p50": obs_metrics.percentile(samples, 0.5),
                        "p99": obs_metrics.percentile(samples, 0.99),
                        "n": len(samples)}
        return out

    # -- engine loop --------------------------------------------------------

    def has_work(self) -> bool:
        return bool(self._pending or self._slots)

    def alive(self) -> bool:
        """Step-thread liveness, the signal the fleet Router's health
        probes and the EngineSupervisor read: False once shut down OR
        once a started step thread died (crash/stranded state).  An
        engine that was never start()ed counts as alive — it is driven
        by explicit step() calls."""
        if self._stop:
            return False
        t = self._thread
        return t is None or t.is_alive()

    def step(self) -> bool:
        """One engine iteration: reap cancelled/expired requests, admit
        pending requests into free slots (resuming preempted ones first —
        they re-enter at the queue head), advance every active slot one
        token (preempting victims when page allocation fails), evict
        finished sequences.  Returns True when any work was done."""
        self.stats["steps_total"] += 1
        # named fault point for the step loop itself: an InjectedFault
        # here is caught by _loop's backstop (fails in-flight, keeps
        # serving); an InjectedCrash (BaseException) escapes it and KILLS
        # the step thread with handles stranded and slots held — the
        # replica-death shape the fleet tier must survive
        self._fire("step")
        with self.tracer.span("engine_step"):
            reaped = self._reap()
            admitted = self._admit()
            decoded = self._decode_step()
        return reaped or admitted or decoded

    def start(self):
        """Run the engine loop in a background thread (serving mode)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def shutdown(self, timeout: float = 10.0):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            # a mid-step thread owns the cache: releasing slots/pages under
            # it would hand the same pages to two sequences.  Re-join once
            # (a long decode step can outlive the first timeout), then
            # REFUSE to touch slot/page state while it is still alive.
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                err = EngineStopped("engine shut down (step thread wedged)")
                with self._cv:
                    for req in list(self._pending):
                        self.stats["failed"] += 1
                        req._resolve(err)
                    self._pending.clear()
                raise RuntimeError(
                    f"engine step thread still running after "
                    f"{2 * timeout:.0f}s; queued requests were failed but "
                    "slots/pages were NOT released (the thread owns them) — "
                    "retry shutdown() once it finishes its step")
            self._thread = None
        # thread is gone (or never ran): fail anything still queued or in
        # flight so waiters unblock, and reclaim the slots.  Under _cv: a
        # client thread's cancel() also removes/resolves pending requests,
        # and racing it here would double-resolve a handle.  EngineStopped
        # (a RuntimeError) so the fleet Router classifies these as replica
        # death and retries the zero-token ones elsewhere.
        err = EngineStopped("engine shut down")
        with self._cv:
            for req in list(self._pending):
                # terminal-counter identity (accepted == sum of outcomes)
                # holds through shutdown: force-resolved counts as failed
                self.stats["failed"] += 1
                req._resolve(err)
            self._pending.clear()
            for slot in list(self._slots):
                st = self._slots.pop(slot)
                self.stats["failed"] += 1
                st.req._resolve(err)
                self.cache.release_slot(slot)

    def _loop(self):
        while True:
            with self._cv:
                while not self._stop and not self.has_work():
                    self._cv.wait()
                if self._stop:
                    return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — backstop: step()
                # handles its own dispatch faults; anything escaping is an
                # engine bug — fail in-flight work so waiters unblock
                self._fail_inflight(e)
            except BaseException:  # noqa: BLE001 — InjectedCrash (chaos)
                # or interpreter teardown: the step thread dies RIGHT HERE
                # with slots held and handles unresolved.  No cleanup by
                # design — this is replica death, the shape the fleet
                # supervisor must prove it recovers from (shutdown() on
                # the dead engine resolves the strands; the Router
                # re-places what is safely recoverable).
                return

    def _recover_pools(self, cause: BaseException) -> bool:
        """If a failed donated dispatch consumed the k/v pools, re-zero
        them and fail every in-flight slot (their cached KV is gone).
        Returns True when recovery ran.  No-op while the buffers are
        alive (CPU, or a failure before dispatch)."""
        cache = self.cache
        try:
            dead = any(getattr(a, "is_deleted", lambda: False)()
                       for a in (cache.pools["k"], cache.pools["v"]))
        except Exception:  # noqa: BLE001 — treat unknown state as dead
            dead = True
        if not dead:
            return False
        err = RuntimeError(f"KV pools lost to a failed donated dispatch "
                           f"({cause!r:.120}); slot state was reset")
        for slot in list(self._slots):
            self._evict(slot, err, "failed")
        cache.pools = generation.init_paged_kv_pools(
            self.config, cache.num_pages, cache.page_size)
        return True

    # -- internals ----------------------------------------------------------

    def _fire(self, point: str, **ctx) -> None:
        if self.faults is not None:
            self.faults.fire(point, engine=self, **ctx)

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _sample(self, logits):
        return generation.sample_logits(
            logits, self._next_key(), self.temperature, self.top_k,
            self.top_p)

    def _reap(self) -> bool:
        """Resolve cancelled and past-deadline requests, queued or in
        flight, releasing any slot/pages they hold."""
        now = time.monotonic()
        did = False
        with self._cv:
            for req in list(self._pending):
                if req.cancelled:
                    err = RequestCancelled("request cancelled")
                    key = "cancelled"
                elif req.deadline is not None and now >= req.deadline:
                    err = DeadlineExceeded("deadline expired while queued")
                    key = "timed_out"
                else:
                    continue
                self._pending.remove(req)
                self.stats[key] += 1
                req._resolve(err)
                did = True
        for slot in list(self._slots):
            st = self._slots.get(slot)
            if st is None:
                continue
            if st.req.cancelled:
                self._evict(slot, RequestCancelled("request cancelled"),
                            "cancelled")
                did = True
            elif st.req.deadline is not None and now >= st.req.deadline:
                self._evict(slot, DeadlineExceeded(
                    f"deadline expired after {len(st.req.tokens)} tokens"),
                    "timed_out")
                did = True
        return did

    def _evict(self, slot: int, err: BaseException, stat_key: str) -> None:
        st = self._slots.pop(slot)
        self.cache.release_slot(slot)
        self.tracer.instant("evict", slot=slot, reason=stat_key)
        with self._cv:
            self.stats[stat_key] += 1
        st.req._resolve(err)

    def _pick_victim(self) -> int:
        if self.victim_policy == "fewest_tokens":
            # least work lost; tie -> latest admitted
            return min(self._slots, key=lambda s: (
                len(self._slots[s].req.tokens), -self._slots[s].admit_seq))
        return max(self._slots, key=lambda s: self._slots[s].admit_seq)

    def _preempt(self, slot: int) -> None:
        """Release a victim's pages and re-queue it at the HEAD of the
        pending deque, carrying a host copy of its KV pages (swap mode) or
        nothing (recompute mode)."""
        cache = self.cache
        st = self._slots.pop(slot)
        pages = list(cache._slot_pages[slot])
        rs = _ResumeState(ctx=st.ctx, last_tok=st.last_tok,
                          n_pages=len(pages))
        self.tracer.instant("preempt", slot=slot, ctx=st.ctx,
                            mode=self.preempt_mode)
        try:
            if self.preempt_mode == "swap":
                with self.tracer.span("swap_out", slot=slot,
                                      pages=len(pages)):
                    self._fire("swap_out", slot=slot, pools=cache.pools)
                    idx = np.zeros((cache.pages_per_seq,), np.int32)
                    idx[:len(pages)] = pages
                    hk, hv = self._swap_out(cache.pools["k"],
                                            cache.pools["v"],
                                            jnp.asarray(idx))
                    rs.host_k = np.asarray(hk)   # device -> host RAM
                    rs.host_v = np.asarray(hv)
        except Exception as e:  # noqa: BLE001 — a failed swap-out loses the
            # victim's KV: fail that request, keep the engine serving
            cache.release_slot(slot)
            with self._cv:
                self.stats["failed"] += 1
            st.req._resolve(e)
            self._recover_pools(e)
            return
        cache.release_slot(slot)
        st.req._resume = rs
        with self._cv:
            self._pending.appendleft(st.req)
            self.stats["preemptions"] += 1

    def _admit(self) -> bool:
        cache = self.cache
        progress = False
        while True:
            with self._cv:
                if not self._pending or cache.free_slot_count == 0:
                    break
                req = self._pending[0]
                rs = req._resume
                need = (rs.n_pages if rs is not None
                        else cache.pages_needed(req.prompt.size))
                if need > cache.free_page_count:
                    break  # head-of-line waits for pages (no reordering)
                self._pending.popleft()
            slot = cache.acquire_slot()
            self._admit_seq += 1
            if req.cancelled:   # cancelled between submit and admission
                cache.release_slot(slot)
                with self._cv:
                    self.stats["cancelled"] += 1
                req._resolve(RequestCancelled("request cancelled"))
                progress = True
                continue
            try:
                with self.tracer.span("admit", slot=slot,
                                      resume=rs is not None):
                    if rs is not None:
                        self._resume_into(slot, req, rs)
                    else:
                        self._prefill_into(slot, req)
            except Exception as e:  # noqa: BLE001 — admission must not leak
                # the request left _pending but never (or only briefly)
                # reached _slots: without cleanup the slot and its pages
                # leak forever and result() blocks until timeout.  Release
                # both, resolve the handle with the error, and keep
                # admitting — a per-request failure (e.g. a prefill OOM at
                # this bucket size) must not wedge the engine.
                self._slots.pop(slot, None)
                if slot in cache._slot_pages:
                    cache.release_slot(slot)
                with self._cv:
                    self.stats["failed"] += 1
                req._resolve(e)
                # _prefill/_swap_in DONATE the pools: a dispatch that fails
                # after donation has already consumed them (TPU; CPU
                # ignores donation), and every later prefill/decode would
                # die on deleted buffers.  Re-zero the pools and fail the
                # slots whose KV lived in them.
                self._recover_pools(e)
            progress = True
        return progress

    def _prefill_into(self, slot: int, req: _Request) -> None:
        """Fresh admission: reserve the prompt's pages only (admit-on-
        demand), prefill, sample the first token."""
        cache = self.cache
        S = req.prompt.size
        self._fire("page_alloc", slot=slot, n_tokens=S)
        cache.ensure_capacity(slot, S)
        if req.t_admit is None:     # first admission only (not resume)
            req.t_admit = time.monotonic()
            self._h_queue_wait.observe(req.t_admit - req.t_submit)
        # menu lookup (the default menu's top bucket is clamped to the
        # rope table — a non-pow2 max_position_embeddings would
        # otherwise over-slice it)
        Sb = self._bucket_for(S)
        ids = np.zeros((1, Sb), np.int32)
        ids[0, :S] = req.prompt
        with self.tracer.span("prefill", slot=slot, tokens=S,
                              bucket=Sb) as sp:
            self._fire("prefill", slot=slot, pools=cache.pools)
            last, k_pool, v_pool = self._prefill(
                self.params, jnp.asarray(ids), cache.pools["k"],
                cache.pools["v"], cache.page_table[slot][None],
                jnp.int32(S))
            sp.fence((last, k_pool))
        cache.pools = {"k": k_pool, "v": v_pool}
        with self.tracer.span("sample", slot=slot):
            self._fire("sample", slot=slot)
            tok = int(np.asarray(self._sample(last))[0])
        req.tokens.append(tok)
        now = time.monotonic()
        if req.t_first_token is None:
            req.t_first_token = now
            self._h_ttft.observe(now - req.t_submit)
        req.t_last_token = now
        with self._cv:
            self.stats["admitted"] += 1
        if (req.eos_id is not None and tok == req.eos_id) \
                or req.max_new_tokens == 1:
            self._finish(slot, req)
        else:
            self._slots[slot] = _SlotState(req, tok, ctx=S,
                                           admit_seq=self._admit_seq)

    def _resume_into(self, slot: int, req: _Request,
                     rs: _ResumeState) -> None:
        """Re-admit a preempted request: reallocate its page count, then
        either scatter the host KV copy back (swap) or re-prefill
        prompt+generated-so-far (recompute).  Token-exact either way: the
        cache ends bit-identical (swap) or recomputed through the same
        prefill math the fresh path uses (recompute)."""
        cache = self.cache
        self._fire("page_alloc", slot=slot,
                   n_tokens=rs.n_pages * cache.page_size)
        cache.ensure_capacity(slot, rs.n_pages * cache.page_size)
        if rs.host_k is not None:
            with self.tracer.span("swap_in", slot=slot,
                                  pages=rs.n_pages) as sp:
                self._fire("swap_in", slot=slot, pools=cache.pools)
                idx = np.zeros((cache.pages_per_seq,), np.int32)
                pages = cache._slot_pages[slot]
                idx[:len(pages)] = pages
                k_pool, v_pool = self._swap_in(
                    cache.pools["k"], cache.pools["v"], jnp.asarray(idx),
                    jnp.asarray(rs.host_k), jnp.asarray(rs.host_v))
                sp.fence(k_pool)
            cache.pools = {"k": k_pool, "v": v_pool}
            with self._cv:
                self.stats["swapped_in"] += 1
        else:
            # recompute-on-resume: the cached part is prompt + all
            # generated tokens except the pending one (ctx tokens total);
            # re-prefill it through the same bucketed path admission uses
            ids_np = np.concatenate(
                [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
            Sb = self._bucket_for(rs.ctx)
            ids = np.zeros((1, Sb), np.int32)
            ids[0, :rs.ctx] = ids_np
            with self.tracer.span("prefill", slot=slot, tokens=rs.ctx,
                                  bucket=Sb, resume=True) as sp:
                self._fire("prefill", slot=slot, pools=cache.pools)
                _last, k_pool, v_pool = self._prefill(
                    self.params, jnp.asarray(ids), cache.pools["k"],
                    cache.pools["v"], cache.page_table[slot][None],
                    jnp.int32(rs.ctx))
                sp.fence(k_pool)
            cache.pools = {"k": k_pool, "v": v_pool}
        with self._cv:
            self.stats["resumed"] += 1
        req._resume = None
        self._slots[slot] = _SlotState(req, rs.last_tok, ctx=rs.ctx,
                                       admit_seq=self._admit_seq)

    def _decode_step(self) -> bool:
        if not self._slots:
            return False
        cache = self.cache
        # on-demand page allocation: the incoming token lands at cache
        # index st.ctx — under pressure, preempt a victim and retry.
        # Never the last runnable sequence (its worst case was validated
        # at submit), so a lone request always completes.
        for slot in sorted(self._slots):
            if slot not in self._slots:
                continue        # preempted as a victim earlier in the pass
            st = self._slots[slot]
            while True:
                try:
                    self._fire("page_alloc", slot=slot, n_tokens=st.ctx + 1)
                    cache.ensure_capacity(slot, st.ctx + 1)
                    break
                except RuntimeError as e:
                    if len(self._slots) == 1:
                        # last runnable: a pool too small for one sequence
                        # is rejected at submit(), so this is an injected
                        # or configuration fault — fail the request rather
                        # than deadlock
                        self._evict(slot, e, "failed")
                        break
                    victim = self._pick_victim()
                    self._preempt(victim)
                    if victim == slot or slot not in self._slots:
                        # preempted ourselves — or a failed swap-out
                        # recovered the pools and failed this slot too
                        break
        if not self._slots:
            return True         # every slot preempted/evicted this pass
        B = cache.max_slots
        toks = np.zeros((B,), np.int32)
        ctx = np.zeros((B,), np.int32)   # empty slots hit the scratch page
        for slot, st in self._slots.items():
            toks[slot] = st.last_tok
            ctx[slot] = st.ctx
        try:
            with self.tracer.span("decode_step",
                                  active=len(self._slots)) as sp:
                self._fire("decode", pools=cache.pools)
                logits, pools = self._decode(
                    self.params, jnp.asarray(toks), jnp.asarray(ctx),
                    cache.page_table, cache.pools["k"], cache.pools["v"])
                sp.fence(logits)
            cache.pools = pools
            with self.tracer.span("sample"):
                self._fire("sample")
                nxt = np.asarray(self._sample(logits))
        except Exception as e:  # noqa: BLE001 — dispatch/sampling fault:
            # the donated pools may be consumed and this step's KV writes
            # are suspect.  Fail every in-flight request, recover the
            # pools, keep serving the queue.
            self._fail_inflight(e)
            return True
        with self._cv:
            self.stats["decode_steps"] += 1
            self.stats["decode_tokens"] += len(self._slots)
        now = time.monotonic()
        for slot in list(self._slots):
            st = self._slots[slot]
            st.ctx += 1
            tok = int(nxt[slot])
            st.req.tokens.append(tok)
            st.last_tok = tok
            if st.req.t_last_token is not None:
                self._h_itl.observe(now - st.req.t_last_token)
            st.req.t_last_token = now
            if (st.req.eos_id is not None and tok == st.req.eos_id) \
                    or len(st.req.tokens) >= st.req.max_new_tokens:
                del self._slots[slot]
                self._finish(slot, st.req)
        return True

    def _fail_inflight(self, e: BaseException) -> None:
        for slot in list(self._slots):
            self._evict(slot, e, "failed")
        self._recover_pools(e)

    def _finish(self, slot: int, req: _Request):
        self.cache.release_slot(slot)
        with self._cv:
            self.stats["completed"] += 1
        if req.t_admit is not None and req.tokens:
            dur = time.monotonic() - req.t_admit
            if dur > 0:
                self._h_tps.observe(len(req.tokens) / dur)
        req._resolve()


def serve_llm(engine: LLMEngine, host: str = "127.0.0.1", port: int = 0,
              max_body_bytes: int = 8 * 1024 * 1024,
              request_timeout: float = 300.0):
    """HTTP JSON generation endpoint over a continuous-batching engine.

    POST / with {"prompt": [token ids], "max_new_tokens": N,
    "eos_id": optional, "deadline": optional seconds} returns
    {"tokens": [...]}.  Concurrent requests share the engine's decode
    batch (continuous batching), so throughput scales with occupancy, not
    request count.

    Failure surface: a full pending queue replies 503 with a Retry-After
    header; a request that misses `request_timeout` replies 504 AND is
    cancelled so its slot/pages free immediately (it must not starve the
    batch until max_new_tokens); GET /healthz replies 200 only while the
    engine's step thread is alive; GET /stats returns a locked snapshot
    of the engine counters (Content-Type: application/json); GET /metrics
    renders the same registry as Prometheus text exposition format
    (Content-Type: text/plain; version=0.0.4) with the TTFT /
    inter-token / queue-wait histograms.  Returns (server, thread);
    server.shutdown() stops the HTTP loop AND the engine."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    engine.start()

    class Handler(BaseHTTPRequestHandler):
        def _reply_text(self, status: int, text: str, content_type: str,
                        headers=None):
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply(self, status: int, payload: dict, headers=None):
            self._reply_text(status, json.dumps(payload),
                             "application/json", headers)

        def do_GET(self):
            path = self.path.rstrip("/")
            if path == "/stats":
                self._reply(200, engine.stats_snapshot())
            elif path == "/metrics":
                reg = getattr(engine, "metrics", None)
                if reg is None:
                    self._reply(404, {"error": "engine has no metrics "
                                               "registry"})
                    return
                self._reply_text(200, reg.render(),
                                 "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                t = engine._thread
                alive = (t is not None and t.is_alive()
                         and not engine._stop)
                self._reply(200 if alive else 503,
                            {"ok": alive,
                             "step_thread_alive": bool(t and t.is_alive()),
                             "stopped": engine._stop})
            else:
                self._reply(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", "0"))
                if n > max_body_bytes:
                    self._reply(413, {"error": "body too large"})
                    return
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = req["prompt"]
                    max_new = int(req.get("max_new_tokens", 16))
                    eos_id = req.get("eos_id")
                    deadline = req.get("deadline")
                except (json.JSONDecodeError, KeyError, TypeError,
                        ValueError) as e:
                    self._reply(400, {"error": f"bad request body: {e!r}"})
                    return
                try:
                    handle = engine.submit(prompt, max_new, eos_id,
                                           deadline=deadline)
                except QueueFull as e:
                    retry = max(1, int(-(-e.retry_after // 1)))
                    self._reply(503, {"error": str(e)},
                                headers={"Retry-After": str(retry)})
                    return
                except (ValueError, RuntimeError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                try:
                    toks = handle.result(timeout=request_timeout)
                except TimeoutError as e:
                    # covers both the wait timeout and an engine-side
                    # DeadlineExceeded; cancel so the slot/pages free NOW
                    handle.cancel()
                    self._reply(504, {"error": f"generation timed out: {e}"})
                    return
                except RequestCancelled as e:
                    self._reply(409, {"error": str(e)})
                    return
                self._reply(200, {"tokens": toks})
            except Exception as e:  # noqa: BLE001 — server-side fault
                self._reply(500, {"error": repr(e)})

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    _orig_shutdown = srv.shutdown

    def _shutdown():
        _orig_shutdown()
        engine.shutdown()

    srv.shutdown = _shutdown
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t
