"""EngineSupervisor — detect a dead replica, rebuild it from a factory.

An LLMEngine is preemption-safe *within* one replica (PR 4): dispatch
faults, OOM, deadlines and shutdown all provably leak nothing.  What it
cannot survive is itself: a step thread killed mid-step (an
InjectedCrash in chaos runs; a segfaulting kernel, an OOM-killed
runtime, a wedged device in production) strands every queued and
in-flight handle and holds the dead engine's slots forever.  The
reference framework keeps ~56k LoC of fleet machinery for exactly this
(paddle/fluid/distributed); this module is the minimal TPU-native
analog:

  * `check(engine)` classifies an engine: "ok", "dead_thread" (started
    step thread no longer alive, not a clean stop), "pools_lost" (a k/v
    pool buffer is deleted AND STAYS deleted across a recheck — the
    in-step recovery path never ran or failed), or "stopped";
  * `rebuild(engine)` tears the dead engine down — `shutdown()` on a
    crashed engine resolves every stranded handle with `EngineStopped`
    and reclaims slot accounting — and returns a fresh engine from the
    factory, bounded by `max_rebuilds`.

The fleet Router calls these from its health loop and re-registers the
replacement under the same replica id; `supervise()` is the standalone
one-shot (no router) convenience the tests exercise directly.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

__all__ = ["EngineSupervisor"]


class EngineSupervisor:
    """Rebuild policy for dead LLMEngine replicas.

    factory: zero-arg callable returning a fresh, fault-free engine.
    max_rebuilds: total rebuild budget across all replicas (None =
    unbounded) — a crash-looping replica must not rebuild forever.
    recheck_after: seconds between the two looks of the pools-lost
    check (a donated dispatch deletes pools *transiently* mid-step on
    TPU; only a deletion that persists is unrecoverable).
    """

    def __init__(self, factory: Callable[[], object],
                 max_rebuilds: Optional[int] = 16,
                 shutdown_timeout: float = 10.0,
                 recheck_after: float = 0.05):
        self.factory = factory
        self.max_rebuilds = max_rebuilds
        self.shutdown_timeout = float(shutdown_timeout)
        self.recheck_after = float(recheck_after)
        self.rebuilds = 0

    # -- detection ----------------------------------------------------------

    def _pools_deleted(self, engine) -> bool:
        try:
            pools = engine.cache.pools
            return any(getattr(pools[s], "is_deleted", lambda: False)()
                       for s in ("k", "v"))
        except Exception:  # noqa: BLE001 — unreadable state counts as lost
            return True

    def check(self, engine) -> str:
        """Classify an engine: 'ok' | 'stopped' | 'dead_thread' |
        'pools_lost'.  Cheap enough for a health loop; the pools check
        double-reads across `recheck_after` so a transient mid-dispatch
        donation is never mistaken for an unrecoverable loss."""
        if engine._stop:
            return "stopped"
        t = engine._thread
        if t is not None and not t.is_alive():
            return "dead_thread"
        if self._pools_deleted(engine):
            time.sleep(self.recheck_after)
            if self._pools_deleted(engine):
                return "pools_lost"
        return "ok"

    # -- recovery -----------------------------------------------------------

    def rebuild(self, engine, start: bool = False, teardown: bool = True):
        """Tear down a dead engine and return a replacement from the
        factory, or None when the rebuild budget is exhausted.

        `shutdown()` on the dead engine is the handle-resolution step:
        every stranded queued/in-flight request resolves with
        `EngineStopped` there, which is what lets the Router's retry
        logic see them (requeue iff zero tokens) instead of losing them
        silently.  teardown=False skips it when the caller already shut
        the engine down (the Router's death path) — a WEDGED step thread
        makes each shutdown block its full join timeout, and the single
        health-tick thread must not pay that twice per death.  start=True
        starts the replacement's step thread (threaded fleets); manual
        fleets leave it to the pump."""
        if self.max_rebuilds is not None \
                and self.rebuilds >= self.max_rebuilds:
            return None
        if teardown:
            try:
                engine.shutdown(timeout=self.shutdown_timeout)
            except Exception:  # noqa: BLE001 — a wedged step thread:
                # shutdown already failed the queued handles; the slots
                # stay with the zombie, the replacement engine gets a
                # fresh pool anyway
                pass
        new = self.factory()
        self.rebuilds += 1
        if start:
            new.start()
        return new

    def supervise(self, engine, start: bool = False):
        """One-shot standalone supervision: check, and rebuild when the
        verdict demands it.  Returns (verdict, engine) where `engine` is
        the replacement on rebuild (or the original when 'ok'/'stopped'
        or the budget is spent)."""
        verdict = self.check(engine)
        if verdict in ("dead_thread", "pools_lost"):
            new = self.rebuild(engine, start=start)
            if new is not None:
                return verdict, new
        return verdict, engine
