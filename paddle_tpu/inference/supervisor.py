"""EngineSupervisor — detect a dead replica, rebuild it from a factory.

An LLMEngine is preemption-safe *within* one replica (PR 4): dispatch
faults, OOM, deadlines and shutdown all provably leak nothing.  What it
cannot survive is itself: a step thread killed mid-step (an
InjectedCrash in chaos runs; a segfaulting kernel, an OOM-killed
runtime, a wedged device in production) strands every queued and
in-flight handle and holds the dead engine's slots forever.  The
reference framework keeps ~56k LoC of fleet machinery for exactly this
(paddle/fluid/distributed); this module is the minimal TPU-native
analog:

  * `check(engine)` classifies an engine: "ok", "dead_thread" (started
    step thread no longer alive, not a clean stop), "pools_lost" (a k/v
    pool buffer is deleted AND STAYS deleted across a recheck — the
    in-step recovery path never ran or failed), or "stopped";
  * `rebuild(engine)` tears the dead engine down — `shutdown()` on a
    crashed engine resolves every stranded handle with `EngineStopped`
    and reclaims slot accounting — and returns a fresh engine from the
    factory, bounded by `max_rebuilds`.

The fleet Router calls these from its health loop and re-registers the
replacement under the same replica id; `supervise()` is the standalone
one-shot (no router) convenience the tests exercise directly.

`BurnRateAutoscaler` closes the QoS control loop on the same factory:
the Router's health tick feeds it the fleet's per-tenant SLO burn rates
(inference/qos.py tenancy -> obs/slo.py burn gauges), and sustained
high-priority burn above `high_burn` for `sustain_ticks` consecutive
ticks SPAWNS a replica (factory() + Router.register); sustained
recovery below `low_burn` drains and releases the most recently
spawned one (Router.release).  Hysteresis on both edges — an
oscillating burn signal must not thrash replicas — and only replicas
the autoscaler spawned are ever released: the base fleet is the
operator's, not the control loop's.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

__all__ = ["EngineSupervisor", "BurnRateAutoscaler"]


class EngineSupervisor:
    """Rebuild policy for dead LLMEngine replicas.

    factory: zero-arg callable returning a fresh, fault-free engine.
    max_rebuilds: total rebuild budget across all replicas (None =
    unbounded) — a crash-looping replica must not rebuild forever.
    recheck_after: seconds between the two looks of the pools-lost
    check (a donated dispatch deletes pools *transiently* mid-step on
    TPU; only a deletion that persists is unrecoverable).
    """

    def __init__(self, factory: Callable[[], object],
                 max_rebuilds: Optional[int] = 16,
                 shutdown_timeout: float = 10.0,
                 recheck_after: float = 0.05):
        self.factory = factory
        self.max_rebuilds = max_rebuilds
        self.shutdown_timeout = float(shutdown_timeout)
        self.recheck_after = float(recheck_after)
        self.rebuilds = 0

    # -- detection ----------------------------------------------------------

    def _pools_deleted(self, engine) -> bool:
        try:
            pools = engine.cache.pools
            return any(getattr(pools[s], "is_deleted", lambda: False)()
                       for s in ("k", "v"))
        except Exception:  # noqa: BLE001 — unreadable state counts as lost
            return True

    def check(self, engine) -> str:
        """Classify an engine: 'ok' | 'stopped' | 'dead_thread' |
        'pools_lost'.  Cheap enough for a health loop; the pools check
        double-reads across `recheck_after` so a transient mid-dispatch
        donation is never mistaken for an unrecoverable loss."""
        if engine._stop:
            return "stopped"
        t = engine._thread
        if t is not None and not t.is_alive():
            return "dead_thread"
        if self._pools_deleted(engine):
            time.sleep(self.recheck_after)
            if self._pools_deleted(engine):
                return "pools_lost"
        return "ok"

    # -- recovery -----------------------------------------------------------

    def rebuild(self, engine, start: bool = False, teardown: bool = True):
        """Tear down a dead engine and return a replacement from the
        factory, or None when the rebuild budget is exhausted.

        `shutdown()` on the dead engine is the handle-resolution step:
        every stranded queued/in-flight request resolves with
        `EngineStopped` there, which is what lets the Router's retry
        logic see them (requeue iff zero tokens) instead of losing them
        silently.  teardown=False skips it when the caller already shut
        the engine down (the Router's death path) — a WEDGED step thread
        makes each shutdown block its full join timeout, and the single
        health-tick thread must not pay that twice per death.  start=True
        starts the replacement's step thread (threaded fleets); manual
        fleets leave it to the pump."""
        if self.max_rebuilds is not None \
                and self.rebuilds >= self.max_rebuilds:
            return None
        if teardown:
            try:
                engine.shutdown(timeout=self.shutdown_timeout)
            except Exception:  # noqa: BLE001 — a wedged step thread:
                # shutdown already failed the queued handles; the slots
                # stay with the zombie, the replacement engine gets a
                # fresh pool anyway
                pass
        new = self.factory()
        self.rebuilds += 1
        if start:
            new.start()
        return new

    def supervise(self, engine, start: bool = False):
        """One-shot standalone supervision: check, and rebuild when the
        verdict demands it.  Returns (verdict, engine) where `engine` is
        the replacement on rebuild (or the original when 'ok'/'stopped'
        or the budget is spent)."""
        verdict = self.check(engine)
        if verdict in ("dead_thread", "pools_lost"):
            new = self.rebuild(engine, start=start)
            if new is not None:
                return verdict, new
        return verdict, engine


class BurnRateAutoscaler:
    """Per-tenant SLO burn -> fleet size, with hysteresis on both edges.

    Control signal: the WORST burn rate over every high-priority tenant
    (priority <= `max_priority`) on every live replica, read from
    `engine.tenant_burn_rates()` — the same windowed numbers the
    per-tenant `/metrics` gauges render, never re-derived.  Low-tier
    tenants never scale the fleet: a flooding bulk tenant is the WFQ
    queue's problem, not a reason to buy hardware.

    Policy: burn >= `high_burn` for `sustain_ticks` CONSECUTIVE router
    ticks spawns one replica from `factory` (falling back to the
    router's supervisor factory) and registers it into rotation, up to
    `max_extra` beyond the base fleet; burn <= `low_burn` for
    `sustain_ticks` consecutive ticks drains and releases the most
    recently spawned replica.  The band between the thresholds holds
    steady (and resets both streaks), so a burn signal oscillating
    around one threshold cannot thrash replicas.  Only replicas this
    loop spawned are ever released — the operator's base fleet is not
    the control loop's to shrink.

    A factory that RAISES at spawn time black-boxes the fleet (best-
    effort FlightRecorder dump on a live replica, tagged
    `autoscale_spawn_failed`) and leaves the fleet at its current size:
    a broken scale-up path must be diagnosable from the dump, never a
    crashed health tick.

    Wire-up: `Router(..., autoscaler=BurnRateAutoscaler(...))`; the
    router calls `observe(router)` once per health tick after probes
    and death handling, so the loop always sees post-recovery burn."""

    def __init__(self, factory: Optional[Callable[[], object]] = None,
                 high_burn: float = 2.0, low_burn: float = 0.5,
                 sustain_ticks: int = 3, max_extra: int = 2,
                 max_priority: int = 0):
        if float(low_burn) >= float(high_burn):
            raise ValueError(
                f"low_burn ({low_burn}) must be < high_burn "
                f"({high_burn}) — the hysteresis band cannot be empty")
        if int(sustain_ticks) < 1:
            raise ValueError("sustain_ticks must be >= 1")
        self.factory = factory
        self.high_burn = float(high_burn)
        self.low_burn = float(low_burn)
        self.sustain_ticks = int(sustain_ticks)
        self.max_extra = int(max_extra)
        self.max_priority = int(max_priority)
        self._hot_streak = 0
        self._cool_streak = 0
        self._spawned: List[int] = []   # rids we registered, newest last
        self.spawns = 0
        self.releases = 0
        self.spawn_failures = 0
        self.last_burn = 0.0

    # -- signal -------------------------------------------------------------

    def _fleet_burn(self, router) -> float:
        """Worst high-priority tenant burn across live replicas.  A
        replica whose accessor is missing or raises contributes nothing
        (stale telemetry degrades the signal, never crashes the tick)."""
        worst = 0.0
        for r in router.replicas:
            if r.dead:
                continue
            fn = getattr(r.engine, "tenant_burn_rates", None)
            if fn is None:
                continue
            try:
                rates = fn(max_priority=self.max_priority)
            except Exception:  # noqa: BLE001 — dying replica mid-read
                continue
            for v in rates.values():
                if v > worst:
                    worst = v
        return worst

    def snapshot(self) -> dict:
        return {
            "last_burn": self.last_burn,
            "spawned_rids": list(self._spawned),
            "spawns": self.spawns,
            "releases": self.releases,
            "spawn_failures": self.spawn_failures,
            "hot_streak": self._hot_streak,
            "cool_streak": self._cool_streak,
        }

    # -- control loop -------------------------------------------------------

    def observe(self, router) -> None:
        """One control-loop step; called by Router.tick()."""
        burn = self._fleet_burn(router)
        self.last_burn = burn
        if burn >= self.high_burn:
            self._cool_streak = 0
            self._hot_streak += 1
            if self._hot_streak >= self.sustain_ticks \
                    and len(self._spawned) < self.max_extra:
                self._hot_streak = 0
                self._spawn(router)
        elif burn <= self.low_burn:
            self._hot_streak = 0
            self._cool_streak += 1
            if self._cool_streak >= self.sustain_ticks \
                    and self._spawned:
                self._cool_streak = 0
                self._release(router)
        else:
            # inside the hysteresis band: hold fleet size, reset both
            # streaks — sustained means CONSECUTIVE, not cumulative
            self._hot_streak = 0
            self._cool_streak = 0

    def _spawn(self, router) -> None:
        factory = self.factory
        if factory is None and router.supervisor is not None:
            factory = router.supervisor.factory
        if factory is None:
            return
        try:
            engine = factory()
        except Exception:  # noqa: BLE001 — broken scale-up path: dump
            self.spawn_failures += 1
            for r in router.replicas:
                fl = getattr(r.engine, "flight", None)
                if fl is not None:
                    try:
                        fl.dump("autoscale_spawn_failed")
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                    break
            return
        rep = router.register(engine)
        self._spawned.append(rep.rid)
        self.spawns += 1

    def _release(self, router) -> None:
        rid = self._spawned.pop()
        if router.release(rid):
            self.releases += 1
        else:
            # refused (unknown rid after an operator removal, or the
            # fleet would empty): keep tracking it, retry next cycle
            self._spawned.append(rid)
