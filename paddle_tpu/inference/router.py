"""Fleet-tier serving: a Router fronting N LLMEngine replicas.

One LLMEngine is preemption-safe but mortal: a dead step thread takes
every queued request with it.  The reference framework's answer is its
fleet layer (paddle/fluid/distributed, ~56k LoC of brpc services); the
TPU-native answer here is one Router object and three rules:

  placement   — least-loaded: replicas are scored from their own obs
                metrics GAUGES (`llm_queue_depth` + `llm_slots_in_flight`,
                free pages as the tiebreak) — the same numbers
                `GET /metrics` exposes, read from the registry, never
                re-derived (the PR 6 signal plane is the source of truth).
                PREFIX AFFINITY biases the load score: each replica's
                prefix-index digest (the root token chunks of its radix
                index) is cached per health tick, and a request whose
                leading tokens match a replica's cached prefix gets a
                sub-unit load discount there — ties (and only mild
                imbalance) break toward the replica already holding the
                prefix, so fleet-wide hit rate compounds instead of
                spraying identical system prompts across replicas.
                Affinity NEVER outvotes health: ejected/dead replicas
                are not candidates at all.
  health      — every replica is probed on a tick (step-thread liveness +
                supervisor pool checks); a failing probe EJECTS the
                replica from placement.  Reinstatement must be EARNED:
                after an exponential backoff the router sends a canary
                request through the replica and only a completed canary
                returns it to rotation (a flapping replica pays a doubled
                backoff per failed canary).
  retry       — when a replica dies, its stranded requests resolve with
                `EngineStopped`; the Router re-places a request iff NO
                tokens were resolved (a partially-decoded request is not
                safely retryable — it fails with a typed `ReplicaDied`,
                never silently, never twice).  Each hop carries the
                REMAINING deadline, and the retry budget (`max_hops`) is
                decremented across hops; exhaustion is a typed
                `RetriesExhausted`.  A retry that finds no capacity is
                PARKED and re-placed by the health tick — accepted work
                is never dropped on the floor.

Backpressure composes upward: every healthy replica refusing with
`QueueFull` makes `submit()` raise `FleetQueueFull` carrying the MINIMUM
Retry-After among replicas (`serve_fleet` maps it to HTTP 503); zero
healthy replicas raise `NoHealthyReplica`.  `drain()` stops placement,
finishes in-flight work, and only then lets `shutdown()` stop the
engines.

Replica death is handled, not hidden: the health tick detects the dead
step thread, `shutdown()` on the dead engine resolves every stranded
handle, a sweep catches requests stranded mid-admission (crashed between
queue and slot), and the `EngineSupervisor` rebuilds the replica from
its factory and re-registers it under the same id — it then re-enters
rotation through the same canary gate as any ejected replica.

Chaos surface: the router fires the fleet fault points
(`replica_death`, `health_flap`, `stats_staleness`, `slow_replica` —
see inference/faults.py) at its health probes and score reads;
`faults.fleet_check_invariants` proves no request is lost or
double-resolved, retried outputs are token-exact against a single
healthy engine, and every live replica leaks zero pages/slots.
`tools/chaos_fleet.py` is the soak CLI; `tests/test_router_chaos.py`
ships the deterministic schedules.

Threading modes: `threaded=True` (serving) starts every engine's step
thread plus a router health-tick thread; `threaded=False` (deterministic
chaos schedules) runs nothing in the background — `pump()` executes one
health tick and one step of every live replica.

DISAGGREGATED serving (`roles="prefill=1,decode=2"`): replicas are
classed prefill/decode, placement is role-aware (fresh requests steer to
prefill-class replicas, handoff continuations to decode-class — a large
but FINITE penalty, so a sole surviving wrong-class replica still
serves), and a prefill replica resolving a hop with `PrefillHandoff`
makes the router BROKER the staged KV pages to a decode replica
(`import_prefix`) and re-place the request there with `handoff=False`.
The handoff resolves with ZERO tokens by construction, so every
mid-transfer death falls under the existing retry rule: re-place with
the remaining deadline, nothing stranded.  Under sustained per-class
load imbalance the health tick FLIPS a replica's role (hysteresis:
`role_flip_ticks` consecutive imbalanced ticks, donor class keeps >= 1
replica) — roles live outside every compiled program, so a flip costs
zero recompiles.  A shared `kvstore=` (TieredPrefixStore) rides along:
evicted prefixes demote into it, admissions promote from it, and the
affinity score learns its digest so a demoted-but-warm prefix still
attracts placement (at half the device-tier discount).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import List, Optional, Sequence

from . import faults as _faults
from . import qos as _qos
from .llm_engine import (DeadlineExceeded, EngineStopped, LLMEngine,
                         PrefillHandoff, QueueFull, RequestCancelled,
                         _StatsDict)
from .supervisor import EngineSupervisor
from ..obs import metrics as obs_metrics
from ..obs import reqtrace as obs_reqtrace

__all__ = ["Router", "Replica", "FleetHandle", "serve_fleet",
           "FleetQueueFull", "NoHealthyReplica", "ReplicaDied",
           "RetriesExhausted", "RouterStopped",
           "HEALTHY", "EJECTED", "CANARY"]

HEALTHY = "healthy"     # in placement rotation
EJECTED = "ejected"     # out of rotation, waiting out its backoff
CANARY = "canary"       # earning reinstatement via a probe request


class FleetQueueFull(QueueFull):
    """Every healthy replica refused with QueueFull: fleet-wide
    backpressure.  retry_after is the MINIMUM across replicas — the
    soonest any queue could drain.  serve_fleet maps this to HTTP 503
    with a Retry-After header."""


class NoHealthyReplica(RuntimeError):
    """Zero replicas in rotation (all ejected/dead).  Transient when a
    supervisor is rebuilding; serve_fleet maps it to HTTP 503."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = float(retry_after)


class ReplicaDied(RuntimeError):
    """Terminal: the serving replica died AFTER tokens were resolved, so
    the request is not safely retryable (a blind retry could hand the
    client a different chain than the tokens it may already have seen).
    Typed and explicit — never a silent loss."""


class RetriesExhausted(RuntimeError):
    """Terminal: the request survived zero-token replica deaths but the
    cross-hop retry budget ran out."""


class RouterStopped(RuntimeError):
    """submit() refused: the router is draining or shut down."""


class FleetHandle:
    """One fleet-level request: the client-facing handle whose lifetime
    may span several engine-level hops.  Resolved EXACTLY once fleet-wide
    (resolutions counts every attempt so fleet_check_invariants can prove
    it); `hops` lists the replica ids tried in order."""

    def __init__(self, router: "Router", prompt: Sequence[int],
                 max_new_tokens: int, eos_id: Optional[int],
                 deadline: Optional[float], max_hops: int,
                 req_id: Optional[str] = None,
                 tenant: str = _qos.DEFAULT_TENANT, priority: int = 1):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        # multi-tenant QoS labels: resolved ONCE at fleet submission and
        # carried on every hop, so a retry re-places under the same
        # weight/tier as the original admission
        self.tenant = str(tenant)
        self.priority = int(priority)
        # the fleet trace context: every engine-level hop carries this
        # id (and its hop index), so the request's whole cross-replica
        # journey shares ONE timeline in the obs request registry
        self.req_id = req_id or obs_reqtrace.new_request_id()
        # absolute, fixed at FLEET submission: every hop re-derives its
        # remaining budget from this, so retries never get fresh time
        self._deadline = (None if deadline is None
                          else time.monotonic() + float(deadline))
        self.hops_left = int(max_hops)
        self.hops: List[int] = []
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.cancelled = False
        self.resolutions = 0
        self._router = router
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._hop = None            # current engine-level _Request
        self._handled = None        # last hop whose resolution we consumed
        self._is_parked = False
        # disaggregation: once a prefill replica resolves with
        # PrefillHandoff, the payload rides the handle (it survives
        # parking and decode-side retries) and every later placement is
        # a CONTINUATION — imported into the target, submitted with
        # handoff=False so it can never ping-pong back
        self._handoff = None
        self._continuation = False

    def remaining_deadline(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return self._deadline - time.monotonic()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes fleet-wide; returns the
        generated tokens.  Raises the typed terminal error otherwise."""
        if not self._event.wait(timeout):
            raise TimeoutError("fleet generation did not finish in time")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def cancel(self) -> None:
        """Cancel wherever the request currently is: a parked retry
        resolves at the next tick; a placed hop is cancelled in its
        engine (the resolution flows back through the router).  No-op
        once done."""
        with self._lock:
            if self._event.is_set():
                return
            self.cancelled = True
            hop, parked = self._hop, self._is_parked
        if not parked and hop is not None:
            hop.cancel()
        # parked (or pre-attach): the next tick's parked sweep resolves it

    def _resolve(self, error: Optional[BaseException] = None) -> None:
        with self._lock:
            self.resolutions += 1
            if self._event.is_set():
                return
            self.error = error
            self._event.set()


class Replica:
    """One engine slot in the fleet: identity (stable across rebuilds),
    health state machine, and the set of fleet handles currently placed
    on it (the router's death-sweep source)."""

    def __init__(self, rid: int, engine: LLMEngine):
        self.rid = int(rid)
        self.engine = engine
        # the replica's CLASS ("mixed"/"prefill"/"decode") — the fleet-
        # durable copy: a rebuilt engine is re-stamped from this, and a
        # role flip updates both
        self.role = getattr(engine, "role", "mixed")
        self.state = HEALTHY
        self.dead = False          # torn down, awaiting rebuild/permanent
        self.crashed = False       # manual-mode: step() raised InjectedCrash
        self.backoff = 0.0
        self.ejected_until = 0.0
        self.canary = None         # in-flight canary _Request
        self.canary_t0 = 0.0
        self.inflight: set = set()
        self.rebuilds = 0
        self.deaths = 0

    def thread_dead(self) -> bool:
        """A started step thread that is no longer alive and was NOT
        cleanly stopped — the crashed-replica signature."""
        e = self.engine
        t = e._thread
        return t is not None and not t.is_alive() and not e._stop


def _parse_roles(roles, n: int) -> List[str]:
    """Normalize a fleet role spec to one role string per replica.
    Accepts "prefill=1,decode=2" (class counts, assigned to replicas in
    order, remainder "mixed") or a per-replica sequence like
    ("prefill", "decode", "decode")."""
    valid = ("mixed", "prefill", "decode")
    if isinstance(roles, str):
        out: List[str] = []
        for part in roles.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, cnt = part.partition("=")
            name = name.strip()
            if name not in valid:
                raise ValueError(
                    f"unknown replica role {name!r}; valid: {valid}")
            out.extend([name] * int(cnt or 1))
        if len(out) > n:
            raise ValueError(
                f"role spec names {len(out)} replicas, fleet has {n}")
        out.extend(["mixed"] * (n - len(out)))
        return out
    out = [str(x) for x in roles]
    if len(out) != n:
        raise ValueError(
            f"per-replica role list has {len(out)} entries, "
            f"fleet has {n}")
    for name in out:
        if name not in valid:
            raise ValueError(
                f"unknown replica role {name!r}; valid: {valid}")
    return out


class Router:
    """Least-loaded router over N LLMEngine replicas.  See the module
    docstring for the placement/health/retry rules.

    engines: the replicas (or pass factory=/num_replicas= to build them).
    supervisor: EngineSupervisor used to rebuild dead replicas (defaults
    to one over `factory` when given; None = dead replicas stay dead).
    faults: optional FaultInjector fired at the fleet fault points.
    max_hops: cross-replica retry budget per request.
    threaded: True starts engine step threads + a health-tick thread;
    False is the deterministic chaos mode driven by pump().
    """

    _STATS_KEYS = (
        "accepted", "rejected", "placed", "retries", "parked", "completed",
        "failed", "cancelled", "timed_out", "ejections", "reinstatements",
        "canaries", "deaths", "rebuilds", "handoffs", "role_flips",
        "autoscale_ups", "autoscale_downs")
    _STATS_HELP = {
        "handoffs": "prefill->decode KV handoffs brokered",
        "role_flips": "replica role flips under sustained load imbalance",
        "autoscale_ups": "replicas spawned by the burn-rate autoscaler",
        "autoscale_downs": "autoscaled replicas drained and released",
        "accepted": "fleet requests accepted (a FleetHandle exists)",
        "rejected": "fleet submits refused (backpressure / no replica)",
        "placed": "engine-level placements (hops), incl. retries",
        "retries": "zero-token requests re-placed after replica death",
        "parked": "retries parked for lack of capacity (placed later)",
        "completed": "fleet requests resolved with tokens",
        "failed": "fleet requests resolved with a terminal error",
        "cancelled": "fleet requests resolved by cancellation",
        "timed_out": "fleet requests resolved by deadline expiry",
        "ejections": "replicas removed from placement by health probes",
        "reinstatements": "replicas returned to rotation by a canary",
        "canaries": "canary probe requests sent to ejected replicas",
        "deaths": "replica deaths detected (dead step thread / crash)",
        "rebuilds": "replicas rebuilt from the supervisor's factory",
    }

    # role-aware placement: a LARGE but FINITE load penalty for placing
    # on the wrong class (fresh work on a decode replica, a handoff
    # continuation on a prefill replica) — finite so a sole surviving
    # wrong-class replica still beats rejecting the request outright
    ROLE_PENALTY = 1000.0

    def __init__(self, engines: Optional[Sequence[LLMEngine]] = None, *,
                 factory=None, num_replicas: Optional[int] = None,
                 supervisor: Optional[EngineSupervisor] = None,
                 faults=None, max_hops: int = 3,
                 prefix_affinity: float = 0.5,
                 roles=None, kvstore=None, autoscaler=None,
                 role_flip_ticks: int = 3, role_flip_ratio: float = 2.0,
                 health_interval: float = 0.05,
                 backoff_base: float = 0.1, backoff_max: float = 5.0,
                 canary_timeout: float = 30.0,
                 engine_shutdown_timeout: float = 10.0,
                 threaded: bool = True,
                 metrics: Optional[obs_metrics.Registry] = None,
                 reqtrace: Optional[obs_reqtrace.RequestRegistry] = None):
        if engines is None:
            if factory is None:
                raise ValueError("pass engines= or factory=")
            engines = [factory() for _ in range(num_replicas or 2)]
        engines = list(engines)
        if not engines:
            raise ValueError("a fleet needs at least one replica")
        if supervisor is None and factory is not None:
            supervisor = EngineSupervisor(factory)
        self.supervisor = supervisor
        self.replicas = [Replica(i, e) for i, e in enumerate(engines)]
        self.reqtrace = reqtrace if reqtrace is not None \
            else obs_reqtrace.get_request_registry()
        # stamp each engine with its replica id AND the fleet's request
        # registry: timelines key replica tracks on the name, and a
        # request's engine-level edges must land in the SAME ring as
        # the router's fleet edges — a custom `reqtrace=` that only
        # reached the router would silently split every timeline in two
        for r in self.replicas:
            r.engine.replica_name = str(r.rid)
            r.engine.reqtrace = self.reqtrace
        self.faults = faults
        self.max_hops = int(max_hops)
        # sub-unit by default: with integer queue/slot loads, affinity
        # breaks ties toward the prefix-holding replica but a replica
        # one whole request busier still wins — and it can never outvote
        # health ejection, which removes a replica from candidacy
        self.prefix_affinity = float(prefix_affinity)
        self._prefix_digests: dict = {}     # rid -> root token chunks
        # -- disaggregation: replica classes + the shared host KV tier.
        # `roles` is "prefill=1,decode=2" (counts, remainder mixed) or a
        # per-replica sequence; role lives on the Replica (fleet-durable
        # across rebuilds) and is mirrored onto the engine, which is
        # what actually changes behavior (auto-handoff at prefill_done).
        if roles is not None:
            for r, role in zip(self.replicas,
                               _parse_roles(roles, len(self.replicas))):
                r.role = role
                r.engine.role = role
        self.kvstore = kvstore
        if kvstore is not None:
            for r in self.replicas:
                if hasattr(r.engine, "attach_kvstore"):
                    r.engine.attach_kvstore(kvstore)
        # burn-rate autoscaler (supervisor.BurnRateAutoscaler or any
        # object with observe(router)): consulted once per health tick,
        # AFTER probes/deaths so it sees post-recovery burn.  None = the
        # fleet size is static.
        self.autoscaler = autoscaler
        self._host_digest: tuple = ()       # kvstore root chunks, per tick
        self._tier_hits = {"device": 0, "host": 0}
        # role-flip hysteresis: flip only after `role_flip_ticks`
        # CONSECUTIVE ticks of >`role_flip_ratio`x per-replica class
        # load imbalance, and only while the donor class keeps >= 1
        self.role_flip_ticks = int(role_flip_ticks)
        self.role_flip_ratio = float(role_flip_ratio)
        self._flip_streak = 0
        self._flip_toward = None
        self.health_interval = float(health_interval)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.canary_timeout = float(canary_timeout)
        self.engine_shutdown_timeout = float(engine_shutdown_timeout)
        self.threaded = bool(threaded)
        self._lock = threading.RLock()
        self._parked: collections.deque = collections.deque()
        self._stopping = False
        self._stop_health = False
        self._health_thread: Optional[threading.Thread] = None
        self.metrics = metrics if metrics is not None \
            else obs_metrics.Registry()
        self.stats = _StatsDict(self.metrics, self._STATS_KEYS,
                                prefix="fleet", help=self._STATS_HELP)
        reg = self.metrics
        self._h_placement = reg.histogram(
            "fleet_placement_seconds",
            "submit() -> engine placement (score + hop submit)",
            buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1,
                     0.5, 1.0))
        reg.gauge("fleet_replicas", "replicas registered").set_function(
            lambda: len(self.replicas))
        reg.gauge("fleet_replicas_healthy", "replicas in placement rotation"
                  ).set_function(lambda: sum(
                      1 for r in self.replicas
                      if r.state == HEALTHY and not r.dead))
        reg.gauge("fleet_parked_now", "retries currently awaiting capacity"
                  ).set_function(lambda: len(self._parked))
        reg.gauge("fleet_inflight_now",
                  "fleet handles currently placed on a replica"
                  ).set_function(lambda: sum(
                      len(r.inflight) for r in self.replicas))
        # fleet-wide pool headroom: the sum the per-replica
        # llm_free_pages gauges render individually — one number for
        # dashboards and the capacity-planning view of the memory
        # telemetry each engine samples per step
        reg.gauge("fleet_free_pages_total",
                  "free KV pages summed over live replicas"
                  ).set_function(lambda: sum(
                      r.engine.cache.free_page_count
                      for r in self.replicas if not r.dead))
        # fleet-wide prefix hit rate: the compounding signal the
        # affinity score exists to maximize — cumulative hits / lookups
        # summed over live replicas (0.0 before any admission looked up)
        reg.gauge("fleet_prefix_hit_rate",
                  "cumulative prefix-cache hits / lookups across live "
                  "replicas").set_function(self._prefix_hit_rate)
        # which TIER earned the affinity discount at scoring time: a
        # rising host share means placement is being steered by
        # demoted-but-warm prefixes (device evicted, host tier intact)
        reg.gauge("fleet_prefix_tier_hit_rate",
                  "share of placement affinity hits served by the host "
                  "KV tier").set_function(lambda: (
                      self._tier_hits["host"]
                      / max(1, self._tier_hits["host"]
                            + self._tier_hits["device"])))
        if self.threaded:
            for r in self.replicas:
                r.engine.start()
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True)
            self._health_thread.start()

    # -- client surface -----------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline: Optional[float] = None,
               max_hops: Optional[int] = None,
               req_id: Optional[str] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None) -> FleetHandle:
        """Place a request on the least-loaded healthy replica.  Raises
        FleetQueueFull when EVERY healthy replica refuses (min
        Retry-After attached), NoHealthyReplica when rotation is empty,
        RouterStopped while draining, ValueError for requests no replica
        could ever serve — including a non-positive or non-finite
        `deadline` (validated HERE, at submission: a deadline that could
        never be met must fail typed at the front door, not burn a
        placement only to be reaped in some engine's admission sweep).
        tenant/priority: QoS labels resolved against the fleet's policy
        (replica 0's table — one factory builds every replica, so the
        tables agree) and carried across every hop and retry.  req_id:
        optional trace id (serve_fleet passes the client's); the
        handle's `req_id` keys the request's cross-replica timeline
        (`GET /debug/request/<id>`)."""
        if self._stopping:
            raise RouterStopped("router is draining/stopped")
        if deadline is not None:
            d = float(deadline)
            if not math.isfinite(d) or d <= 0.0:
                raise ValueError(
                    f"deadline must be a finite number of seconds > 0, "
                    f"got {deadline!r}")
        tname, eff_priority, _ = self._resolve_qos(tenant, priority)
        fh = FleetHandle(self, prompt, max_new_tokens, eos_id, deadline,
                         self.max_hops if max_hops is None else max_hops,
                         req_id=req_id, tenant=tname,
                         priority=eff_priority)
        self._rq_event(fh, "fleet_submit",
                       prompt_tokens=len(fh.prompt),
                       max_new_tokens=fh.max_new_tokens,
                       tenant=fh.tenant, priority=fh.priority)
        t0 = time.monotonic()
        try:
            placed, retry_after, saw_queue_full = self._try_place(
                fh, count_accepted=True)
        except ValueError:
            self.stats.inc("rejected")   # malformed for EVERY replica
            self._rq_event(fh, "fleet_reject", reason="invalid")
            raise
        self._h_placement.observe(time.monotonic() - t0)
        if placed:
            return fh
        self.stats.inc("rejected")
        if saw_queue_full:
            self._rq_event(fh, "fleet_reject", reason="queue_full")
            raise FleetQueueFull(
                "every healthy replica is at queue capacity",
                retry_after=retry_after if retry_after else 1.0)
        self._rq_event(fh, "fleet_reject", reason="no_healthy_replica")
        raise NoHealthyReplica(
            "no healthy replica available (all ejected, dead, or dying)")

    def _resolve_qos(self, tenant, priority):
        """Resolve QoS labels against the fleet's tenant table: replica
        0's engine policy (every replica comes from one factory, so the
        tables agree).  UnknownTenant/ValueError propagate to submit()'s
        caller BEFORE a FleetHandle exists — a mislabeled request never
        burns a placement attempt."""
        policy = None
        with self._lock:       # register/release mutate the list live
            replicas = list(self.replicas)
        for r in replicas:
            policy = getattr(r.engine, "qos", None)
            if policy is not None:
                break
        if policy is None:
            policy = _qos.QoSPolicy()
        return policy.resolve(tenant, priority)

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int, eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> List[List[int]]:
        """Synchronous convenience mirroring LLMEngine.generate."""
        handles = [self.submit(p, max_new_tokens, eos_id) for p in prompts]
        if not self.threaded:
            _faults.drive_fleet(self, handles, settle=False)
            timeout = 0
        return [h.result(timeout=timeout) for h in handles]

    def stats_snapshot(self) -> dict:
        with self._lock:
            snap = dict(self.stats)
            snap["replicas"] = len(self.replicas)
            snap["healthy_replicas"] = sum(
                1 for r in self.replicas
                if r.state == HEALTHY and not r.dead)
            snap["parked_now"] = len(self._parked)
            snap["replica_states"] = {
                r.rid: ("dead" if r.dead else r.state)
                for r in self.replicas}
            snap["replica_roles"] = {r.rid: r.role
                                     for r in self.replicas}
            snap["affinity_tier_hits"] = dict(self._tier_hits)
            if self.kvstore is not None:
                snap["kvstore"] = self.kvstore.snapshot()
        return snap

    # -- placement ----------------------------------------------------------

    def _fire(self, point: str, **ctx) -> None:
        if self.faults is None:
            return
        try:
            self.faults.fire(point, router=self, **ctx)
        except _faults.InjectedCrash as e:
            # crash=True on a ROUTER-level point: there is no step thread
            # to kill here, and InjectedCrash is a BaseException that
            # would sail past the health loop's backstop and silently
            # kill the tick thread — degrade it to the typed fault every
            # fire site already handles.
            raise _faults.InjectedFault(str(e)) from e

    def _rq_event(self, fh: FleetHandle, name: str, **attrs) -> None:
        """One fleet-level edge on the request's timeline, stamped
        "router" (engine-level events carry the replica name instead)."""
        rt = self.reqtrace
        if rt is not None and rt.enabled:
            rt.event(fh.req_id, name, replica="router",
                     hop=len(fh.hops) - 1 if fh.hops else None, **attrs)

    def _refresh_prefix_digest(self, r: Replica) -> None:
        """Cache the replica's prefix-index digest (root token chunks)
        for the affinity score.  Refreshed per health tick — placement
        tolerates a tick of staleness the same way it tolerates gauge
        staleness."""
        idx = getattr(r.engine, "prefix_index", None)
        try:
            digest = () if idx is None else idx.first_chunks()
        except Exception:  # noqa: BLE001 — raced a live step thread
            return
        with self._lock:       # release() drops entries under the lock
            self._prefix_digests[r.rid] = digest

    def _prefix_hit_rate(self) -> float:
        hits = total = 0
        with self._lock:       # register/release mutate the list live
            replicas = list(self.replicas)
        for r in replicas:
            if r.dead:
                continue
            try:
                h = r.engine.stats["prefix_hits"]
                total += h + r.engine.stats["prefix_misses"]
                hits += h
            except Exception:  # noqa: BLE001 — engine without the counters
                pass
        return hits / total if total else 0.0

    def _prefix_affinity_hit(self, r: Replica, prompt):
        """Which cache TIER covers the request's leading tokens on this
        replica: "device" when a root chunk of its radix index is a
        prefix of the prompt (admission there splices at least one page
        directly), "host" when the shared kvstore's digest covers it and
        the replica is attached to the store (admission there PROMOTES
        the demoted pages back — one scatter instead of a re-prefill),
        None otherwise."""
        if not prompt:
            return None
        digest = self._prefix_digests.get(r.rid)
        if digest is None:
            self._refresh_prefix_digest(r)
            digest = self._prefix_digests.get(r.rid, ())
        head = tuple(prompt[:max((len(t) for t in digest), default=0)])
        if any(t and head[:len(t)] == t for t in digest):
            return "device"
        hd = self._host_digest
        if hd and getattr(r.engine, "kvstore", None) is not None:
            head = tuple(prompt[:max(len(t) for t in hd)])
            if any(t and head[:len(t)] == t for t in hd):
                return "host"
        return None

    def _score(self, r: Replica, prompt=None, continuation=False):
        """Least-loaded placement score, SMALLER is better: (queue depth
        + in-flight slots - prefix affinity, -speculative acceptance
        rate, -free pages), read from the replica's metrics GAUGES — the
        same storage its /metrics endpoint renders.  A replica whose
        prefix digest covers the request's leading tokens earns a
        `prefix_affinity` discount on its load (sub-unit: it decides
        ties and mild imbalance, never outvotes a genuinely busier
        queue, and never resurrects an ejected replica — those are not
        candidates).  Acceptance breaks remaining ties: a low-acceptance
        replica burns more verify rows per emitted token (its workload
        drafts badly there), so among equally-loaded replicas the fleet
        learns to place where drafting works.  Replicas that never
        drafted read the neutral 1.0.  A replica whose stats are
        unreadable/stale (fault-injected or a dying engine rendering
        NaN) scores worst-but-placeable: stale telemetry must degrade
        placement, not crash it."""
        stale = (math.inf, 0.0, 0.0)
        try:
            # a slow_replica delay rule stalls HERE — the price of a slow
            # stats read lands on placement latency, nothing breaks
            self._fire("slow_replica", replica=r.rid)
            self._fire("stats_staleness", replica=r.rid)
        except _faults.InjectedFault:
            return stale
        try:
            reg = r.engine.metrics
            q = reg.get("llm_queue_depth").value
            infl = reg.get("llm_slots_in_flight").value
            free_p = reg.get("llm_free_pages").value
        except Exception:  # noqa: BLE001 — unreadable registry == stale
            return stale
        if any(math.isnan(v) for v in (q, infl, free_p)):
            return stale
        accept = 1.0
        try:
            g = reg.get("llm_spec_acceptance_rate")
            if g is not None:
                v = g.value
                if not math.isnan(v):
                    accept = v
        except Exception:  # noqa: BLE001 — acceptance is advisory only
            pass
        load = q + infl
        # role-aware steering: fresh work wants a prefill-class replica,
        # a handoff continuation wants a decode-class one; mixed is
        # always neutral.  The penalty rides the LOAD term so health
        # ejection (not a candidate at all) still dominates it.
        role = r.role
        if role != "mixed":
            want = "decode" if continuation else "prefill"
            if role != want:
                load += self.ROLE_PENALTY
        if prompt is not None and self.prefix_affinity:
            tier = self._prefix_affinity_hit(r, prompt)
            if tier == "device":
                load -= self.prefix_affinity
            elif tier == "host":
                # a demoted-but-warm prefix still attracts placement,
                # at half weight: a device-tier splice beats a promote
                load -= 0.5 * self.prefix_affinity
            if tier is not None:
                self._tier_hits[tier] += 1
        return (load, -accept, -free_p)

    def _candidates(self, prompt=None,
                    continuation=False) -> List[Replica]:
        with self._lock:
            cands = [r for r in self.replicas
                     if r.state == HEALTHY and not r.dead]
        return sorted(cands,
                      key=lambda r: self._score(r, prompt, continuation))

    def _try_place(self, fh: FleetHandle, count_accepted: bool = False):
        """Try each healthy replica best-score-first.  Returns (placed,
        min_retry_after_or_None, saw_queue_full) — saw_queue_full
        distinguishes genuine backpressure from a mass-death window
        where every candidate died between probe and submit.  Engine
        submits happen OUTSIDE the router lock: an engine callback
        thread may hold an engine lock while waiting for the router
        lock, so the reverse nesting is forbidden.  count_accepted=True
        (first placement only, never retries) bumps `accepted` AFTER the
        engine took the hop but BEFORE _attach can run an instantly-
        resolving hop's callbacks: a terminal counter never lands ahead
        of accepted, and a refused submit never needs a walk-back (the
        counter stays monotonic for Prometheus rate())."""
        retry_after = None
        value_error = None
        for r in self._candidates(prompt=fh.prompt,
                                  continuation=fh._continuation):
            if fh._continuation and fh._handoff is not None:
                # import the staged KV pages BEFORE submitting: in a
                # threaded fleet the step thread could otherwise admit
                # the continuation ahead of the import and re-prefill
                # from token zero.  If the submit below is then refused
                # (QueueFull) the pages simply stay cached on that
                # replica — warmth, not a leak (the index owns them and
                # LRU/demotion applies as usual).
                try:
                    r.engine.import_prefix(fh._handoff)
                except Exception:  # noqa: BLE001 — stopped/dying replica
                    continue
            try:
                kw = {"handoff": False} if fh._continuation else {}
                hop = r.engine.submit(
                    fh.prompt, fh.max_new_tokens, fh.eos_id,
                    deadline=fh.remaining_deadline(),
                    req_id=fh.req_id, hop=len(fh.hops),
                    tenant=fh.tenant, priority=fh.priority, **kw)
            except QueueFull as e:
                retry_after = (e.retry_after if retry_after is None
                               else min(retry_after, e.retry_after))
                continue
            except EngineStopped:
                # died between probe and submit; the tick will handle it
                continue
            except ValueError as e:
                value_error = e     # malformed for this (hence any) replica
                break
            if count_accepted:
                self.stats.inc("accepted")
            self._attach(fh, r, hop)
            return True, None, False
        if value_error is not None:
            raise value_error
        return False, retry_after, retry_after is not None

    def _attach(self, fh: FleetHandle, r: Replica, hop) -> None:
        with fh._lock:
            fh._hop = hop
            fh.hops.append(r.rid)
        with self._lock:
            r.inflight.add(fh)
            self.stats.inc("placed")
        hop._callbacks.append(
            lambda req, fh=fh, r=r: self._hop_resolved(fh, r, req))
        if fh.cancelled:
            hop.cancel()
        if hop.done():
            # resolved before the callback was registered: deliver
            # manually (idempotent via the _handled guard)
            self._hop_resolved(fh, r, hop)

    # -- hop resolution / retry ---------------------------------------------

    def _hop_resolved(self, fh: FleetHandle, r: Replica, req) -> None:
        """Runs on the resolving thread (engine step thread, canceller,
        or a dead engine's shutdown) — may be invoked more than once for
        one hop (late callback registration); the _handled guard makes
        it exactly-once per hop."""
        with fh._lock:
            if req is not fh._hop or req is fh._handled:
                return
            fh._handled = req
        with self._lock:
            r.inflight.discard(fh)
        err = req.error
        if err is None:
            fh.tokens = list(req.tokens)
            fh._resolve()
            self.stats.inc("completed")
            self._rq_event(fh, "fleet_resolve", outcome="completed",
                           tokens=len(fh.tokens), hops=list(fh.hops))
        elif isinstance(err, RequestCancelled):
            fh._resolve(err)
            self.stats.inc("cancelled")
            self._rq_event(fh, "fleet_resolve", outcome="cancelled")
        elif isinstance(err, DeadlineExceeded):
            fh._resolve(err)
            self.stats.inc("timed_out")
            self._rq_event(fh, "fleet_resolve", outcome="timed_out")
        elif isinstance(err, PrefillHandoff):
            # NOT a failure: a prefill-class replica finished the prefill
            # and exported the KV — broker it to a decode-class replica
            self._broker_handoff(fh, r, err.handoff)
        elif isinstance(err, EngineStopped):
            self._retry_or_fail(fh, r, req)
        else:
            # an engine-level request fault (dispatch error, injected
            # fault, pool loss) on a LIVE replica: passes through typed —
            # the replica itself already recovered
            fh._resolve(err)
            self.stats.inc("failed")
            self._rq_event(fh, "fleet_resolve", outcome="failed")

    def _broker_handoff(self, fh: FleetHandle, r: Replica,
                        handoff) -> None:
        """Route a finished prefill's KV pages to a decode-class replica
        and re-place the request there as a CONTINUATION.  The payload
        rides the handle (it survives parking and later retries), the
        continuation flag flips placement scoring toward decode-class
        and forces `handoff=False` on the next submit (no ping-pong).
        Deliberately NOT charged against hops_left: a handoff is
        forward progress, not a failure — the retry budget stays
        reserved for deaths.  The zero-token handoff contract means a
        decode replica dying later re-enters `_retry_or_fail` with the
        handle still continuation-marked: the pages are re-imported on
        the next placement from the host copy, nothing is stranded."""
        self.stats.inc("handoffs")
        fh._handoff = handoff
        fh._continuation = True
        self._rq_event(fh, "fleet_handoff", src_replica=r.rid,
                       pages=handoff.n_pages, bytes=handoff.nbytes)
        if fh.cancelled:
            fh._resolve(RequestCancelled("request cancelled"))
            self.stats.inc("cancelled")
            self._rq_event(fh, "fleet_resolve", outcome="cancelled")
            return
        rem = fh.remaining_deadline()
        if rem is not None and rem <= 0:
            fh._resolve(DeadlineExceeded(
                f"deadline expired at prefill->decode handoff "
                f"(hops={fh.hops})"))
            self.stats.inc("timed_out")
            self._rq_event(fh, "fleet_resolve", outcome="timed_out")
            return
        if self._stopping:
            fh._resolve(EngineStopped("fleet shut down"))
            self.stats.inc("failed")
            self._rq_event(fh, "fleet_resolve", outcome="fleet_stopped")
            return
        try:
            placed, _, _ = self._try_place(fh)
        except ValueError as e:
            fh._resolve(e)          # no candidate can ever hold it
            self.stats.inc("failed")
            self._rq_event(fh, "fleet_resolve", outcome="failed")
            return
        if not placed:
            self._park(fh)

    def _retry_or_fail(self, fh: FleetHandle, r: Replica, req) -> None:
        """Replica death resolution.  The retry-safety rules, in order:
        tokens resolved -> terminal ReplicaDied; cancelled -> cancelled;
        deadline gone -> DeadlineExceeded (the 504, exactly once); budget
        gone or fleet stopping -> terminal; else decrement the budget and
        re-place with the REMAINING deadline (parking if no capacity)."""
        if req.tokens:
            fh._resolve(ReplicaDied(
                f"replica {r.rid} died after {len(req.tokens)} token(s) "
                "were resolved; not safely retryable"))
            self.stats.inc("failed")
            self._rq_event(fh, "fleet_resolve", outcome="replica_died",
                           replica_id=r.rid, tokens=len(req.tokens))
            return
        if fh.cancelled:
            fh._resolve(RequestCancelled("request cancelled"))
            self.stats.inc("cancelled")
            self._rq_event(fh, "fleet_resolve", outcome="cancelled")
            return
        rem = fh.remaining_deadline()
        if rem is not None and rem <= 0:
            fh._resolve(DeadlineExceeded(
                f"deadline expired during replica-death retry "
                f"(hops={fh.hops})"))
            self.stats.inc("timed_out")
            self._rq_event(fh, "fleet_resolve", outcome="timed_out")
            return
        if self._stopping:
            fh._resolve(EngineStopped("fleet shut down"))
            self.stats.inc("failed")
            self._rq_event(fh, "fleet_resolve", outcome="fleet_stopped")
            return
        if fh.hops_left <= 0:
            fh._resolve(RetriesExhausted(
                f"replica died and the retry budget is exhausted "
                f"(hops={fh.hops})"))
            self.stats.inc("failed")
            self._rq_event(fh, "fleet_resolve",
                           outcome="retries_exhausted")
            return
        fh.hops_left -= 1
        self.stats.inc("retries")
        self._rq_event(fh, "retry", dead_replica=r.rid,
                       hops_left=fh.hops_left)
        try:
            placed, _, _ = self._try_place(fh)
        except ValueError as e:
            # heterogeneous fleet: no CURRENT candidate can hold the
            # request (e.g. the one large-context replica just died) —
            # terminal and typed, never a silently stranded handle
            fh._resolve(e)
            self.stats.inc("failed")
            return
        if not placed:
            self._park(fh)

    def _park(self, fh: FleetHandle) -> None:
        with self._lock:
            fh._is_parked = True
            self._parked.append(fh)
            self.stats.inc("parked")
        self._rq_event(fh, "park")

    def _drain_parked(self) -> None:
        with self._lock:
            if not self._parked:
                return
            batch = list(self._parked)
            self._parked.clear()
            for fh in batch:
                fh._is_parked = False
        for fh in batch:
            if fh.done():
                continue
            if fh.cancelled:
                fh._resolve(RequestCancelled("request cancelled"))
                self.stats.inc("cancelled")
                continue
            rem = fh.remaining_deadline()
            if rem is not None and rem <= 0:
                fh._resolve(DeadlineExceeded(
                    f"deadline expired while parked for retry "
                    f"(hops={fh.hops})"))
                self.stats.inc("timed_out")
                continue
            try:
                placed, _, _ = self._try_place(fh)
            except ValueError as e:
                fh._resolve(e)          # no candidate can ever hold it
                self.stats.inc("failed")
                continue
            if not placed:
                with self._lock:        # re-park silently (no recount)
                    fh._is_parked = True
                    self._parked.append(fh)

    # -- health: probes, ejection, canary, death ----------------------------

    def tick(self) -> None:
        """One health pass: death detection + probe/eject/canary state
        machine per replica, then the parked-retry sweep.  The threaded
        health loop calls this every `health_interval`; manual mode gets
        it via pump()."""
        now = time.monotonic()
        for r in list(self.replicas):
            self._maybe_inject_death(r)
            self._tick_replica(r, now)
            if not r.dead:
                self._refresh_prefix_digest(r)
        if self.kvstore is not None:
            try:
                self._host_digest = self.kvstore.first_chunks()
            except Exception:  # noqa: BLE001 — digest is advisory
                pass
        self._maybe_flip_roles()
        if self.autoscaler is not None and not self._stopping:
            try:
                self.autoscaler.observe(self)
            except Exception:  # noqa: BLE001 — a broken control loop
                pass           # must never take the health tick with it
        self._drain_parked()

    def _maybe_flip_roles(self) -> None:
        """Flip one replica's class under SUSTAINED load imbalance: when
        one class's per-replica load exceeds `role_flip_ratio`x the
        other's for `role_flip_ticks` consecutive ticks and the donor
        class has more than one replica, the donor's least-loaded
        replica joins the hot class.  A role lives entirely outside the
        compiled programs (it only changes where requests are steered
        and whether prefill_done hands off), so a flip costs zero
        recompiles.  Mixed fleets have no classed replicas — no-op."""
        if self._stopping:
            return
        groups = {"prefill": [], "decode": []}
        with self._lock:       # register/release mutate the list live
            replicas = list(self.replicas)
        for r in replicas:
            if r.dead or r.state != HEALTHY:
                continue
            if r.role in groups:
                groups[r.role].append(r)
        pre, dec = groups["prefill"], groups["decode"]
        if not pre or not dec:
            self._flip_streak = 0
            self._flip_toward = None
            return

        def group_load(rs):
            tot = 0.0
            for r in rs:
                try:
                    reg = r.engine.metrics
                    q = reg.get("llm_queue_depth").value
                    infl = reg.get("llm_slots_in_flight").value
                    if not (math.isnan(q) or math.isnan(infl)):
                        tot += q + infl
                except Exception:  # noqa: BLE001 — stale stats read as 0
                    pass
            return tot / max(1, len(rs))

        lp, ld = group_load(pre), group_load(dec)
        # max(.., 1.0) floor: two near-idle classes never look imbalanced
        hot = None
        if lp > self.role_flip_ratio * max(ld, 1.0) and len(dec) > 1:
            hot = "prefill"
        elif ld > self.role_flip_ratio * max(lp, 1.0) and len(pre) > 1:
            hot = "decode"
        if hot is None:
            self._flip_streak = 0
            self._flip_toward = None
            return
        if hot != self._flip_toward:
            self._flip_toward = hot
            self._flip_streak = 1
            return
        self._flip_streak += 1
        if self._flip_streak < self.role_flip_ticks:
            return
        donor = dec if hot == "prefill" else pre
        r = min(donor, key=lambda x: self._score(x))
        with self._lock:
            r.role = hot
            try:
                r.engine.role = hot
            except Exception:  # noqa: BLE001 — dying engine: next tick
                pass           # re-stamps via _handle_death anyway
            self.stats.inc("role_flips")
        self._flip_streak = 0
        self._flip_toward = None

    def _maybe_inject_death(self, r: Replica) -> None:
        try:
            self._fire("replica_death", replica=r.rid)
        except _faults.InjectedFault:
            self.kill(r)

    def kill(self, r: Replica) -> None:
        """Arrange for replica `r` to CRASH at its next engine step (the
        replica_death fault point's effect; also a test hook).  The step
        thread dies exactly as a real mid-step crash would — slots held,
        handles stranded — and the normal death path recovers."""
        eng = r.engine
        if eng.faults is None:
            eng.faults = _faults.FaultInjector([])
        eng.faults.rules.append(
            _faults.FaultRule("step", nth=1, crash=True))
        with eng._cv:
            eng._cv.notify_all()    # wake an idle threaded loop

    def _probe(self, r: Replica) -> bool:
        try:
            self._fire("health_flap", replica=r.rid)
        except _faults.InjectedFault:
            return False            # probe *reports* unhealthy — a flap
        if not r.engine.alive():
            return False
        if self.supervisor is not None \
                and self.supervisor._pools_deleted(r.engine):
            # transient donation windows are invisible here in practice
            # (the probe runs between steps); the supervisor's sticky
            # double-read runs before any rebuild decision
            return self.supervisor.check(r.engine) == "ok"
        return True

    def _detect_dead(self, r: Replica) -> bool:
        return r.crashed or r.thread_dead()

    def _tick_replica(self, r: Replica, now: float) -> None:
        if r.dead:
            return
        if self._detect_dead(r):
            self._handle_death(r)
            return
        if r.state == HEALTHY:
            if not self._probe(r):
                self._eject(r, now, double=False)
        elif r.state == EJECTED:
            if now >= r.ejected_until:
                self._launch_canary(r, now)
        elif r.state == CANARY:
            hop = r.canary
            if hop is None:
                r.state = EJECTED
            elif hop.done():
                r.canary = None
                if hop.error is None and hop.tokens:
                    self._reinstate(r)
                else:
                    self._eject(r, now, double=True)
            elif now - r.canary_t0 > self.canary_timeout:
                hop.cancel()
                r.canary = None
                self._eject(r, now, double=True)

    def _eject(self, r: Replica, now: float, double: bool) -> None:
        with self._lock:
            r.backoff = (min(max(r.backoff, self.backoff_base) * 2,
                             self.backoff_max)
                         if double else self.backoff_base)
            r.ejected_until = now + r.backoff
            r.state = EJECTED
            self.stats.inc("ejections")
        # black-box the ejected replica: the state that failed the probe
        # is what a 3am post-mortem needs (dump() is best-effort/no-raise)
        fl = getattr(r.engine, "flight", None)
        if fl is not None:
            fl.dump("health_ejection")

    def _launch_canary(self, r: Replica, now: float) -> None:
        """Reinstatement is earned: a 1-token probe must COMPLETE through
        the ejected replica before it re-enters rotation."""
        try:
            # a prefill-class replica must DECODE the canary locally: a
            # handoff resolves with zero tokens and would read as
            # failure here forever (the ping-pong trap)
            kw = {"handoff": False} \
                if getattr(r.engine, "role", "mixed") != "mixed" else {}
            hop = r.engine.submit([1], max_new_tokens=1, **kw)
        except Exception:  # noqa: BLE001 — refused/stopped: deeper backoff
            self._eject(r, now, double=True)
            return
        with self._lock:
            r.canary = hop
            r.canary_t0 = now
            r.state = CANARY
            self.stats.inc("canaries")

    def _reinstate(self, r: Replica) -> None:
        with self._lock:
            r.state = HEALTHY
            r.backoff = 0.0
            self.stats.inc("reinstatements")

    def _handle_death(self, r: Replica) -> None:
        """The full replica-death path: eject + mark dead, tear the
        engine down (resolving every handle it knows about), sweep the
        hops stranded mid-admission, then rebuild through the supervisor
        and re-register under the same replica id (re-entering rotation
        via the canary gate)."""
        with self._lock:
            if r.dead:
                return
            r.dead = True
            r.deaths += 1
            r.state = EJECTED
            r.canary = None
            self.stats.inc("deaths")
            self.stats.inc("ejections")
            inflight = list(r.inflight)
            r.inflight.clear()
        # black-box the dead replica BEFORE teardown: shutdown() resolves
        # handles and releases slots, and the dump must show the
        # pre-crash occupancy, not the post-shutdown rubble.  (A threaded
        # engine's dying step thread already dumped "step_thread_death";
        # a pump-mode crash is caught outside the engine, so this is the
        # only dump that replica gets.)
        fl = getattr(r.engine, "flight", None)
        if fl is not None:
            fl.dump("replica_death")
        # capture each stranded request's hop on THIS replica before
        # teardown: shutdown resolutions trigger the retry path, which
        # can re-place a handle onto a healthy replica and swap fh._hop
        # under us — the sweep must resolve only the dead replica's hop
        # objects, never a successor
        stranded = [(fh, fh._hop) for fh in inflight]
        # engine teardown OUTSIDE the router lock (resolutions run router
        # callbacks which need it)
        try:
            r.engine.shutdown(timeout=self.engine_shutdown_timeout)
        except Exception:  # noqa: BLE001 — wedged-thread shutdown already
            pass           # failed the queued handles; proceed to rebuild
        # sweep: a crash mid-admission strands a request in NEITHER
        # _pending NOR _slots — engine shutdown cannot see it.  The
        # router can: every fleet handle placed on this replica whose hop
        # never resolved is force-resolved as replica death (the retry
        # rules then requeue or fail it, never lose it).
        for fh, hop in stranded:
            if hop is not None and not hop.done():
                hop._resolve(EngineStopped(
                    f"replica {r.rid} died mid-request"))
        if self.supervisor is None or self._stopping:
            return
        new = self.supervisor.rebuild(r.engine, start=self.threaded,
                                      teardown=False)
        if new is None:
            return                  # rebuild budget exhausted: stays dead
        now = time.monotonic()
        new.replica_name = str(r.rid)   # keep timelines keyed by rid
        new.reqtrace = self.reqtrace    # ...and in the fleet's registry
        # a rebuilt engine must rejoin its CLASS and the shared host
        # tier — role and store are fleet-side state precisely so a
        # crash can neither demote a replica to mixed nor orphan it
        # from the warm prefixes (cold replica warm-start: its first
        # admissions PROMOTE hot prefixes straight back from the store)
        if r.role != "mixed":
            new.role = r.role
        if self.kvstore is not None and hasattr(new, "attach_kvstore"):
            try:
                new.attach_kvstore(self.kvstore)
            except Exception:  # noqa: BLE001 — page-size mismatch on a
                pass           # heterogeneous rebuild: skip, don't die
        with self._lock:
            r.engine = new
            r.dead = False
            r.crashed = False
            r.rebuilds += 1
            r.state = EJECTED       # earns rotation via the canary gate
            r.backoff = self.backoff_base
            r.ejected_until = now + self.backoff_base
            self.stats.inc("rebuilds")

    # -- elastic fleet: autoscaler add/remove -------------------------------

    def register(self, engine: LLMEngine) -> Replica:
        """Add a NEW replica to the fleet at runtime (the autoscaler's
        scale-up primitive; also a test hook).  The engine is stamped
        exactly like a supervisor rebuild — replica name, fleet request
        registry, shared kvstore — started when the fleet is threaded,
        and enters rotation HEALTHY immediately: a freshly built engine
        has nothing to prove to a canary (it never failed a probe), and
        the whole point of scaling up is capacity NOW."""
        with self._lock:
            rid = 1 + max((r.rid for r in self.replicas), default=-1)
            r = Replica(rid, engine)
            engine.replica_name = str(rid)
            engine.reqtrace = self.reqtrace
            self.replicas.append(r)
            self.stats.inc("autoscale_ups")
        if self.kvstore is not None and hasattr(engine, "attach_kvstore"):
            try:
                engine.attach_kvstore(self.kvstore)
            except Exception:  # noqa: BLE001 — page-size mismatch on a
                pass           # heterogeneous spawn: skip, don't die
        if self.threaded:
            engine.start()
        self._rq_event_fleet("autoscale_up", replica_id=rid)
        return r

    def release(self, rid: int, timeout: Optional[float] = None) -> bool:
        """Remove replica `rid` from the fleet (the autoscaler's
        scale-down primitive).  The replica leaves rotation immediately
        (no new placements), in-flight hops get `timeout` seconds to
        finish (default: the engine shutdown timeout), then the engine
        is shut down — its shutdown resolves any stragglers as
        EngineStopped and the zero-token retry rule re-places them on
        the surviving replicas.  Returns False for an unknown rid or
        when it would empty the fleet."""
        if timeout is None:
            timeout = self.engine_shutdown_timeout
        with self._lock:
            live = [x for x in self.replicas if not x.dead]
            r = next((x for x in self.replicas if x.rid == int(rid)),
                     None)
            if r is None or (not r.dead and len(live) <= 1):
                return False
            r.state = EJECTED           # out of rotation, not a failure
            r.ejected_until = float("inf")
        deadline = time.monotonic() + float(timeout)
        while r.inflight and time.monotonic() < deadline:
            if self.threaded:
                time.sleep(min(0.01, self.health_interval))
            else:
                break       # manual mode: the caller pumps; don't spin
        try:
            r.engine.shutdown(timeout=self.engine_shutdown_timeout)
        except Exception:  # noqa: BLE001 — wedged thread: handles were
            pass           # already failed; proceed to removal
        with self._lock:
            stranded = [(fh, fh._hop) for fh in r.inflight]
            r.inflight.clear()
            try:
                self.replicas.remove(r)
            except ValueError:
                pass
            self._prefix_digests.pop(r.rid, None)
            self.stats.inc("autoscale_downs")
        for fh, hop in stranded:
            if hop is not None and not hop.done():
                hop._resolve(EngineStopped(
                    f"replica {r.rid} released by the autoscaler"))
        self._rq_event_fleet("autoscale_down", replica_id=r.rid)
        return True

    def _rq_event_fleet(self, name: str, **attrs) -> None:
        """A fleet-level trace edge with no request attached (autoscale
        up/down): stamped on a synthetic per-event id so the registry
        keeps an inspectable record without polluting any request's
        timeline."""
        rt = self.reqtrace
        if rt is not None and rt.enabled:
            try:
                rt.event(f"fleet-{name}-{attrs.get('replica_id')}",
                         name, replica="router", **attrs)
            except Exception:  # noqa: BLE001 — tracing is advisory
                pass

    # -- driving ------------------------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop_health:
            try:
                self.tick()
            except _faults.InjectedCrash:
                pass           # BaseException — see _fire; never fatal here
            except Exception:  # noqa: BLE001 — the health loop must
                pass           # survive anything a probe throws
            time.sleep(self.health_interval)

    def pump(self) -> None:
        """Manual-mode fleet iteration (threaded=False): one health tick,
        then one step() of every live replica (mirroring each engine's
        _loop semantics: an escaping Exception fails that replica's
        in-flight work; an InjectedCrash IS replica death)."""
        self.tick()
        for r in list(self.replicas):
            if r.dead:
                continue
            eng = r.engine
            if eng._thread is not None:
                continue            # threaded engine pumps itself
            try:
                if eng.has_work():
                    eng.step()
            except _faults.InjectedCrash:
                r.crashed = True    # handled by the next tick
            except Exception as e:  # noqa: BLE001 — _loop-equivalent
                eng._fail_inflight(e)

    def quiesced(self) -> bool:
        """True when the fleet has no outstanding work anywhere: nothing
        parked, no canary in flight, every live replica HEALTHY with an
        idle engine, no unhandled death.  drive_fleet settles on this."""
        with self._lock:
            if self._parked:
                return False
            for r in self.replicas:
                if r.dead:
                    continue
                if self._detect_dead(r):
                    return False
                if r.state != HEALTHY:
                    return False
                if r.engine.has_work():
                    return False
        return True

    # -- drain / shutdown ---------------------------------------------------

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful drain: stop NEW placement (submit raises
        RouterStopped), keep the health/retry machinery running so
        in-flight and parked work finishes, then terminally fail
        whatever could not complete within the budget (typed, counted —
        never silent)."""
        self._stopping = True
        deadline = time.monotonic() + timeout

        def outstanding():
            with self._lock:
                if self._parked:
                    return True
                return any(r.inflight for r in self.replicas)

        while outstanding() and time.monotonic() < deadline:
            if self.threaded:
                time.sleep(min(0.01, self.health_interval))
            else:
                self.pump()
        with self._lock:
            leftovers = list(self._parked)
            self._parked.clear()
        for fh in leftovers:
            if not fh.done():
                fh._resolve(EngineStopped(
                    "fleet shut down while the request awaited retry"))
                self.stats.inc("failed")

    def shutdown(self, timeout: float = 30.0) -> None:
        """drain(), stop the health loop, shut every engine down (their
        shutdowns resolve residual in-flight handles; the retry path sees
        _stopping and fails them terminally), and sweep any hop stranded
        mid-admission."""
        self.drain(timeout)
        self._stop_health = True
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        with self._lock:       # register/release mutate the list live
            replicas = list(self.replicas)
        for r in replicas:
            try:
                r.engine.shutdown(timeout=self.engine_shutdown_timeout)
            except Exception:  # noqa: BLE001
                pass
            with self._lock:
                inflight = list(r.inflight)
                r.inflight.clear()
            for fh in inflight:
                hop = fh._hop
                if hop is not None and not hop.done():
                    hop._resolve(EngineStopped("fleet shut down"))
        # final parked sweep: the health tick may have POPPED a parked
        # batch right as drain() looked (in neither _parked nor any
        # inflight set) and re-parked it after drain's snapshot — with
        # the health loop now stopped, nothing else would ever resolve
        # it, and an un-timed result() would hang forever
        with self._lock:
            leftovers = list(self._parked)
            self._parked.clear()
        for fh in leftovers:
            if not fh.done():
                fh._resolve(EngineStopped("fleet shut down"))
                self.stats.inc("failed")


def serve_fleet(router: Router, host: str = "127.0.0.1", port: int = 0,
                max_body_bytes: int = 8 * 1024 * 1024,
                request_timeout: float = 300.0):
    """HTTP entry over a fleet Router (the multi-replica serve_llm).

    POST / with {"prompt": [...], "max_new_tokens": N, "eos_id"?,
    "deadline"?, "request_id"?, "tenant"?, "priority"?} returns
    {"tokens": [...], "hops": [replica ids], "request_id": "...",
    "tenant": "...", "priority": N} — tenant/priority echo the RESOLVED
    QoS labels (effective tier after the tenant floor).  The schema is
    CLOSED: an unknown field replies 400 {"error": "unknown_field"}
    instead of being silently dropped, and an unknown tenant under a
    strict policy replies 400 {"error": "unknown_tenant"}.
    `GET /debug/request/<id>`
    returns the request's cross-replica timeline from the router's
    RequestRegistry — fleet placement/retry edges stamped "router",
    engine lifecycle edges stamped with each hop's replica id — or 404
    once evicted from the LRU window.
    Failure surface: fleet backpressure (every replica QueueFull) and an
    empty rotation reply 503 with Retry-After; deadline/timeout replies
    504 AND cancels fleet-wide; a terminal replica-death error
    (ReplicaDied / RetriesExhausted) replies 502 — the upstream died,
    typed, never silent.

    GET /healthz aggregates: 200 while >= 1 replica is in rotation, with
    per-replica {state, alive, rebuilds}.  GET /metrics renders the
    router's own registry PLUS every replica's engine registry stamped
    with a {replica="<id>"} label (obs.metrics.render_merged) — one
    scrape shows fleet counters and per-replica placement signals
    side by side.  GET /stats is the JSON twin.

    Returns (server, thread); server.shutdown() drains the router and
    stops everything."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if not router.threaded:
        raise ValueError("serve_fleet needs a threaded Router "
                         "(Router(..., threaded=True))")

    class Handler(BaseHTTPRequestHandler):
        # the CLOSED request schema: an unknown field is a 400, never a
        # silent drop (a typo'd "prioriti" must not demote a request)
        _POST_FIELDS = frozenset((
            "prompt", "max_new_tokens", "eos_id", "deadline",
            "request_id", "tenant", "priority"))

        def _reply_text(self, status, text, content_type, headers=None):
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply(self, status, payload, headers=None):
            self._reply_text(status, json.dumps(payload),
                             "application/json", headers)

        def do_GET(self):
            path = self.path.rstrip("/")
            if path == "/stats":
                self._reply(200, {
                    "router": router.stats_snapshot(),
                    "replicas": {
                        str(r.rid): r.engine.stats_snapshot()
                        for r in router.replicas},
                })
            elif path.startswith("/debug/request/"):
                rid = path.rsplit("/", 1)[1]
                tl = router.reqtrace.to_dict(rid)
                if tl is None:
                    self._reply(404, {"error": f"unknown request id "
                                               f"{rid!r} (never traced, "
                                               "or evicted)"})
                else:
                    self._reply(200, tl)
            elif path == "/metrics":
                # the router render omits its obs_render_errors_total
                # block and passes the count into the merged family —
                # a metric family must be declared ONCE per scrape or
                # Prometheus parsers reject the whole exposition
                text = router.metrics.render(errors_family=False) \
                    + obs_metrics.render_merged(
                        [(str(r.rid), r.engine.metrics)
                         for r in router.replicas], label="replica",
                        extra_error_counts={
                            "router":
                                router.metrics.render_errors_total})
                self._reply_text(200, text,
                                 "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                healthy = sum(1 for r in router.replicas
                              if r.state == HEALTHY and not r.dead)
                payload = {
                    "ok": healthy >= 1 and not router._stopping,
                    "healthy_replicas": healthy,
                    "replicas": {
                        str(r.rid): {
                            "state": "dead" if r.dead else r.state,
                            "alive": (not r.dead and r.engine.alive()),
                            "rebuilds": r.rebuilds,
                        } for r in router.replicas},
                }
                self._reply(200 if payload["ok"] else 503, payload)
            else:
                self._reply(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", "0"))
                if n > max_body_bytes:
                    self._reply(413, {"error": "body too large"})
                    return
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        self._reply(400, {
                            "error": "bad_body",
                            "detail": "request body must be a JSON "
                                      "object"})
                        return
                    unknown = sorted(set(req) - self._POST_FIELDS)
                    if unknown:
                        self._reply(400, {
                            "error": "unknown_field",
                            "fields": unknown,
                            "detail": f"unknown request field(s): "
                                      f"{', '.join(unknown)}"})
                        return
                    prompt = req["prompt"]
                    max_new = int(req.get("max_new_tokens", 16))
                    eos_id = req.get("eos_id")
                    deadline = req.get("deadline")
                    req_id = req.get("request_id")
                    if req_id is not None:
                        req_id = str(req_id)
                    tenant = req.get("tenant")
                    if tenant is not None:
                        tenant = str(tenant)
                    priority = req.get("priority")
                    if priority is not None:
                        priority = int(priority)
                except (json.JSONDecodeError, KeyError, TypeError,
                        ValueError) as e:
                    self._reply(400, {"error": "bad_body",
                                      "detail": f"bad request body: "
                                                f"{e!r}"})
                    return
                try:
                    handle = router.submit(prompt, max_new, eos_id,
                                           deadline=deadline,
                                           req_id=req_id, tenant=tenant,
                                           priority=priority)
                except _qos.UnknownTenant as e:
                    self._reply(400, {"error": "unknown_tenant",
                                      "tenant": e.tenant,
                                      "detail": str(e)})
                    return
                except (FleetQueueFull, NoHealthyReplica) as e:
                    retry = max(1, int(-(-getattr(e, "retry_after", 1.0)
                                         // 1)))
                    self._reply(503, {"error": str(e)},
                                headers={"Retry-After": str(retry)})
                    return
                except RouterStopped as e:
                    self._reply(503, {"error": str(e)})
                    return
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return
                try:
                    toks = handle.result(timeout=request_timeout)
                except (ReplicaDied, RetriesExhausted) as e:
                    self._reply(502, {"error": str(e)})
                    return
                except EngineStopped as e:
                    # resolved by fleet drain/shutdown mid-request: the
                    # service is going away, not broken — 503 like every
                    # other stop condition
                    self._reply(503, {"error": str(e)})
                    return
                except TimeoutError as e:
                    # wait timeout or DeadlineExceeded; cancel fleet-wide
                    # so no replica keeps decoding for a gone client
                    handle.cancel()
                    self._reply(504, {"error": f"generation timed out: {e}"})
                    return
                except RequestCancelled as e:
                    self._reply(409, {"error": str(e)})
                    return
                self._reply(200, {"tokens": toks, "hops": handle.hops,
                                  "request_id": handle.req_id,
                                  "tenant": handle.tenant,
                                  "priority": handle.priority})
            except Exception as e:  # noqa: BLE001 — server-side fault
                self._reply(500, {"error": repr(e)})

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    _orig_shutdown = srv.shutdown

    def _shutdown():
        _orig_shutdown()
        router.shutdown()

    srv.shutdown = _shutdown
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t
